"""Pluggable filesystem registry for remote path schemes.

Reference parity gap, made explicit: the reference leans on TF's
``tf.io.gfile`` + a Hadoop ``defaultFS`` for ``hdfs://`` model/export
paths (``TFNode.hdfs_path``, ``TFNodeContext.absolute_path`` —
SURVEY.md §2 "TFNode" row). This framework bundles no HDFS/GCS client,
so remote schemes are a *registration point* instead of a silent
pass-through: callers register ``scheme -> opener`` once (e.g. backed by
``fsspec``, ``gcsfs``, or a site-local client) and every path consumer
(``ctx.absolute_path``, TFRecord readers, checkpoint/export helpers)
resolves through here. Unregistered remote schemes fail loudly with a
how-to-fix error rather than a confusing downstream ENOENT.

    from tensorflowonspark_tpu import fs
    fs.register_filesystem("gs", my_gcs_open)      # open(path, mode)
    with fs.open("gs://bucket/data.tfrecord", "rb") as f: ...

Local paths (``file://`` or bare) use the builtin filesystem and never
need registration.
"""

import builtins
import os
import re

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")

_REGISTRY = {}


class UnsupportedSchemeError(RuntimeError):
    """A remote path scheme nobody registered an opener for."""


def require_local(path, what):
    """Fail loudly when a directory-level consumer gets a remote path.

    The registry serves per-FILE opens (TFRecord read/write). Consumers
    that need directory semantics — orbax checkpoints, model export,
    shard listing — require a local/NFS path: an ``opener`` can't
    makedirs/listdir, and orbax brings its own remote backends. Without
    this guard a remote path would be silently written to a local
    directory literally named ``gs:`` (os.path.abspath of a URL).
    """
    if scheme_of(path) is not None:
        raise UnsupportedSchemeError(
            "{} requires a local or NFS path, got {!r}: the fs registry "
            "serves per-file opens only (directory semantics — makedirs/"
            "listdir/atomic rename — need a real filesystem; for remote "
            "checkpoints use orbax's own storage backends, for remote "
            "TFRecords read/write individual files via fs.open)".format(
                what, path))
    return local_part(path)


def scheme_of(path):
    """'hdfs' for 'hdfs://x/y', None for local/bare paths.

    Accepts PathLike (fspath'd first) — pathlib users predate the
    registry and must keep working.
    """
    m = _SCHEME_RE.match(os.fspath(path))
    if not m:
        return None
    s = m.group(1).lower()
    return None if s == "file" else s


def register_filesystem(scheme, opener):
    """Register ``opener(path, mode) -> file object`` for a scheme.

    Returns the previous opener (None if first registration) so tests
    and apps can restore.
    """
    scheme = scheme.lower().rstrip(":")
    prev = _REGISTRY.get(scheme)
    _REGISTRY[scheme] = opener
    _FSSPEC_NEGATIVE.pop(scheme, None)  # re-arm the fallback probe path
    return prev


def unregister_filesystem(scheme):
    _REGISTRY.pop(scheme.lower().rstrip(":"), None)


def is_supported(path):
    """True if :func:`open` can serve this path right now."""
    s = scheme_of(path)
    return s is None or _resolve_opener(s)[0] is not None


def ensure_supported(path):
    """Raise the canonical UnsupportedSchemeError (probe cause chained)
    for a path :func:`open` cannot serve; returns the path otherwise.
    Path consumers that want to fail EARLY (ctx.absolute_path) call this
    instead of duplicating — and drifting from — open()'s message."""
    s = scheme_of(path)
    if s is None:
        return path
    opener, probe_error = _resolve_opener(s)
    if opener is None:
        raise UnsupportedSchemeError(_unsupported_msg(s, path, probe_error)) \
            from probe_error
    return path


def clear_probe_cache():
    """Forget cached fsspec probe failures (e.g. after installing a
    protocol package mid-process)."""
    _FSSPEC_NEGATIVE.clear()


def local_part(path):
    """Strip a file:// prefix; other schemes are returned untouched."""
    path = os.fspath(path)
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


#: schemes fsspec could NOT serve, with the probe error (cleared by an
#: explicit register_filesystem for the scheme): failed plugin imports
#: are not cached in sys.modules, so re-probing per path would redo the
#: whole import attempt in path-resolution loops.
_FSSPEC_NEGATIVE = {}


def _resolve_opener(scheme):
    """(opener, probe_error) for a scheme: explicit registration first,
    then a cached ``fsspec`` protocol fallback.

    fsspec ships in this image and brings protocol plugins
    (``memory://`` out of the box; ``hdfs://`` via pyarrow; ``gs://`` /
    ``s3://`` wherever the extras are installed) — the role Hadoop's
    FileSystem registry played for the reference's ``defaultFS`` paths.
    """
    opener = _REGISTRY.get(scheme)
    if opener is not None:
        return opener, None
    if scheme in _FSSPEC_NEGATIVE:
        return None, _FSSPEC_NEGATIVE[scheme]
    try:
        import fsspec
        fsspec.get_filesystem_class(scheme)  # raises for unknown schemes
    except Exception as e:  # noqa: BLE001 - surfaced via the raise below
        _FSSPEC_NEGATIVE[scheme] = e
        return None, e

    def opener(path, mode):
        import fsspec as _fsspec
        return _fsspec.open(path, mode).open()
    # setdefault: a concurrently registered EXPLICIT opener must win
    return _REGISTRY.setdefault(scheme, opener), None


def _unsupported_msg(s, path, probe_error):
    return (
        "no filesystem registered for {!r} paths ({!r}) and fsspec "
        "could not serve the scheme ({!r}); this framework bundles "
        "no remote-FS client (the reference used TF's gfile+Hadoop)."
        " Either install an fsspec protocol package (gcsfs/s3fs/...) "
        "— the failed probe is cached for this process, so afterwards "
        "call fs.clear_probe_cache() (or restart) — or register an "
        "opener once per process:\n"
        "    from tensorflowonspark_tpu import fs\n"
        "    fs.register_filesystem({!r}, opener)  # opener(path, "
        "mode)".format(s, path, probe_error, s))


def open(path, mode="rb"):  # noqa: A001 - deliberate builtin shadow
    """Open a path through the registered filesystem for its scheme."""
    path = os.fspath(path)
    s = scheme_of(path)
    if s is None:
        return builtins.open(local_part(path), mode)
    opener, probe_error = _resolve_opener(s)
    if opener is None:
        raise UnsupportedSchemeError(_unsupported_msg(s, path, probe_error)) \
            from probe_error
    return opener(path, mode)
