"""Durable control-plane state: the fencing-epoch journal (PR 19).

PR 12 made lease epochs the cluster's split-brain guard: every serving
identity beats with a monotonically minted epoch, and a stale beat is
answered FENCED. That guarantee lived entirely in the reservation
server's memory — kill the driver and a restarted server, having
forgotten every floor, would happily re-mint epoch 1 for an identity
whose real incumbent holds epoch 7. The incumbent's next beat would
then be FENCED by its own *past*, or worse, two replicas could both
hold "current" epochs for one identity. This module is the fix: a
small append-only journal the server fsyncs BEFORE an epoch leaves the
building, so monotonicity survives restart by construction.

Design (deliberately boring — this is the safety floor everything else
stands on):

- **Append-only JSON lines.** One record per line:
  ``{"t": "epoch", "id": <identity>, "e": <int>}`` for lease-epoch
  mints, ``{"t": "control", "e": <int>}`` for control-epoch mints
  (router leadership fencing), ``{"t": "lease", "id": ..., "meta":
  {...}}`` for the latest lease metadata (addr/model/host hints a
  restarted driver can show while replicas re-announce).
- **fsync before reply.** :meth:`record_epoch` returns only after the
  bytes are on disk. A crash landed between fsync and the caller
  seeing the epoch leaves the journal's floor >= anything ever
  *returned* — the safe direction (a floor may exceed reality, never
  trail it).
- **Torn tail is tolerated, torn middle is not.** A crash mid-append
  can leave exactly one partial record — the final line. Recovery
  drops an unparseable FINAL line silently. An unparseable line
  *followed by valid records* means the file was corrupted some other
  way (bit rot, concurrent writer, truncation), and recovery raises
  :class:`JournalCorrupt` LOUDLY: silently continuing could re-mint a
  stale epoch, exactly the failure this journal exists to prevent.
  The operator decides (restore a copy, or deliberately move the file
  aside to accept a cold start) — the code never decides for them.
- **Compaction on rewrite.** When the live file accumulates
  ``compact_every`` appends past the last snapshot, the journal
  rewrites itself as one snapshot record per identity (+ control
  epoch) into a temp file, fsyncs it, and atomically renames over the
  live path (then fsyncs the directory so the rename itself is
  durable). Crash at ANY point leaves either the old complete file or
  the new complete file — never a mix.
"""

import json
import logging
import os
import threading

logger = logging.getLogger(__name__)

#: Default number of appended records after which the journal compacts
#: itself on the next write. Small enough that the file stays a few KB
#: for steady fleets, large enough that compaction is rare.
DEFAULT_COMPACT_EVERY = 4096


class JournalCorrupt(RuntimeError):
    """The journal has an unparseable record that is NOT the final
    line — not a torn append but real corruption. Refusing to load is
    the only safe answer: guessing at floors risks re-minting a stale
    epoch, the exact split-brain this journal prevents."""


class ControlJournal(object):
    """Append-only, fsync'd journal of fencing-epoch floors.

    Thread-safe: every mutation happens under one lock, and writes hit
    disk before the method returns. The reservation server owns the
    canonical instance; tests drive it directly to property-test crash
    interleavings (see tests/test_controlstate.py).
    """

    def __init__(self, path, compact_every=DEFAULT_COMPACT_EVERY):
        self.path = str(path)
        self.compact_every = int(compact_every)
        self._lock = threading.Lock()
        self._epochs = {}        # identity -> highest journaled epoch
        self._control_epoch = 0  # highest journaled control epoch
        self._meta = {}          # identity -> latest lease metadata
        self._appends = 0        # records appended since last snapshot
        self._fh = None
        with self._lock:
            self._recover_locked()
            self._open_append_locked()

    # -- recovery ------------------------------------------------------

    def _recover_locked(self):
        """Replay the journal into the in-memory floors. Tolerates a
        torn FINAL line (crash mid-append); raises JournalCorrupt on
        any earlier unparseable record."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            raw = fh.read()
        lines = raw.split(b"\n")
        # a well-formed file ends with a newline, so the split's last
        # element is empty; anything else is a torn tail candidate
        records, bad_at = [], None
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                bad_at = i
                break
        if bad_at is not None:
            trailing = any(l.strip() for l in lines[bad_at + 1:])
            if trailing:
                raise JournalCorrupt(
                    "journal {} has an unparseable record at line {} "
                    "with valid records after it — refusing to load "
                    "(a guessed floor could re-mint a stale epoch); "
                    "restore the journal or deliberately move it "
                    "aside to accept a cold start".format(
                        self.path, bad_at + 1))
            logger.warning(
                "journal %s: dropping torn final record (crash "
                "mid-append) — %d complete records recovered",
                self.path, len(records))
            # truncate the torn fragment away: otherwise the next
            # append would share its line and the FOLLOWING recovery
            # would drop an acknowledged record with it
            keep = sum(len(l) + 1 for l in lines[:bad_at])
            with open(self.path, "r+b") as fh:
                fh.truncate(keep)
                fh.flush()
                os.fsync(fh.fileno())
        for rec in records:
            t = rec.get("t")
            if t == "epoch":
                ident = rec.get("id")
                self._epochs[ident] = max(
                    self._epochs.get(ident, 0), int(rec.get("e", 0)))
            elif t == "control":
                self._control_epoch = max(
                    self._control_epoch, int(rec.get("e", 0)))
            elif t == "lease":
                self._meta[rec.get("id")] = rec.get("meta") or {}
            # unknown record types are skipped: a newer writer may add
            # kinds an older reader can ignore without losing safety
            # (floors only ever come from records it DOES understand)
        self._appends = len(records)
        if records:
            logger.info(
                "journal %s recovered: %d identities (max epoch %s), "
                "control epoch %d", self.path, len(self._epochs),
                max(self._epochs.values()) if self._epochs else None,
                self._control_epoch)

    def _open_append_locked(self):
        self._fh = open(self.path, "ab")

    # -- views ---------------------------------------------------------

    def epoch_floors(self):
        """{identity: floor} — every epoch ever durably minted (stable
        copy). A restarted server seeds its mint state from this."""
        with self._lock:
            return dict(self._epochs)

    def epoch_floor(self, identity):
        with self._lock:
            return self._epochs.get(identity, 0)

    def control_floor(self):
        """Highest durably minted control epoch (0 = never minted)."""
        with self._lock:
            return self._control_epoch

    def lease_meta(self):
        """{identity: latest journaled lease metadata} (stable copy)."""
        with self._lock:
            return {k: dict(v) for k, v in self._meta.items()}

    # -- writes (fsync before return) ----------------------------------

    def record_epoch(self, identity, epoch):
        """Durably record that ``epoch`` was minted for ``identity``.
        MUST be called before the epoch is returned to any caller: the
        journal's floor must always cover everything the outside world
        has seen. Returns the epoch for chaining."""
        with self._lock:
            epoch = int(epoch)
            self._epochs[identity] = max(
                self._epochs.get(identity, 0), epoch)
            self._append_locked(
                {"t": "epoch", "id": identity, "e": epoch})
        return epoch

    def record_control(self, epoch):
        """Durably record a minted control epoch (router leadership
        fence). Same fsync-before-return contract as record_epoch."""
        with self._lock:
            epoch = int(epoch)
            self._control_epoch = max(self._control_epoch, epoch)
            self._append_locked({"t": "control", "e": epoch})
        return epoch

    def record_lease_meta(self, identity, meta):
        """Durably note ``identity``'s latest lease metadata (small
        JSON-able dict: addr/model/host). Advisory — floors never
        depend on it — so it shares the append path for simplicity."""
        with self._lock:
            self._meta[identity] = dict(meta or {})
            self._append_locked(
                {"t": "lease", "id": identity,
                 "meta": self._meta[identity]})

    def _append_locked(self, rec):
        line = json.dumps(rec, separators=(",", ":")).encode("utf-8") \
            + b"\n"
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._appends += 1
        if self._appends >= self.compact_every:
            self._compact_locked()

    # -- compaction ----------------------------------------------------

    def compact(self):
        """Rewrite the journal as one snapshot record per identity.
        Atomic: crash at any point leaves old-complete or new-complete,
        never a mix."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self):
        tmp = self.path + ".compact"
        with open(tmp, "wb") as fh:
            for ident, epoch in sorted(self._epochs.items(),
                                       key=lambda kv: str(kv[0])):
                fh.write(json.dumps(
                    {"t": "epoch", "id": ident, "e": epoch},
                    separators=(",", ":")).encode("utf-8") + b"\n")
            if self._control_epoch:
                fh.write(json.dumps(
                    {"t": "control", "e": self._control_epoch},
                    separators=(",", ":")).encode("utf-8") + b"\n")
            for ident, meta in sorted(self._meta.items(),
                                      key=lambda kv: str(kv[0])):
                fh.write(json.dumps(
                    {"t": "lease", "id": ident, "meta": meta},
                    separators=(",", ":")).encode("utf-8") + b"\n")
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self.path)
        # fsync the directory so the rename itself survives power loss
        dirfd = os.open(os.path.dirname(os.path.abspath(self.path)),
                        os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._appends = len(self._epochs) + len(self._meta) \
            + (1 if self._control_epoch else 0)
        self._open_append_locked()
        logger.info("journal %s compacted to %d records",
                    self.path, self._appends)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
