"""Generic data-parallel training harness over a device mesh.

The reference delegates the training loop to user code + a
``tf.distribute`` strategy (``MultiWorkerMirroredStrategy`` with NCCL,
SURVEY.md §2.3); the framework's contribution is only wiring. Here the
idiomatic TPU loop *is* part of the framework: params replicated, batch
sharded over the ``data`` mesh axis, one jit-compiled step whose gradient
all-reduce XLA emits over ICI/DCN from the sharding annotations — no
hand-written collectives.

Typical map_fun body::

    def map_fun(args, ctx):
        ctx.initialize_jax()
        trainer = training.Trainer(model=LeNet(), optimizer=optax.adam(1e-3),
                                   mesh=ctx.mesh(),
                                   loss_fn=training.softmax_xent)
        state = trainer.init(rng, sample_batch["x"])
        feed = ctx.get_data_feed(input_mapping={...})
        for batch in infeed.sharded_batches(
                feed.numpy_batches(args.batch_size), trainer.mesh):
            state, metrics = trainer.step(state, batch)
"""

import logging
import time

logger = logging.getLogger(__name__)


def softmax_xent(logits, batch):
    """Mean softmax cross-entropy; expects integer labels in batch['y']."""
    import jax.numpy as jnp
    import optax
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["y"]))


class Trainer(object):
    """Pure-DP trainer: replicated params, batch split over the data axis.

    Args:
      model: a flax ``nn.Module`` whose ``__call__`` takes ``batch['x']``.
      optimizer: an optax ``GradientTransformation``.
      mesh: a ``jax.sharding.Mesh`` with a ``data`` axis (from
        ``ctx.mesh()``); params replicate over every axis.
      loss_fn: ``(logits, batch) -> scalar loss``.
      data_axis: mesh axis name the batch dim is split over.
    """

    def __init__(self, model, optimizer, mesh, loss_fn=softmax_xent,
                 data_axis="data", donate_state=True, train_mode_kwarg="auto",
                 dropout_rng=False, input_keys=("x",), constrain_state=True,
                 remat=False):
        import inspect

        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.data_axis = data_axis
        self.dropout_rng = dropout_rng
        #: batch keys passed positionally to the model, in this order
        #: (e.g. ("input_ids", "attention_mask") for BERT); keys absent
        #: from a batch are skipped, so optional inputs stay optional.
        self.input_keys = tuple(input_keys)
        self.replicated = NamedSharding(mesh, PartitionSpec())
        self.batch_sharding = NamedSharding(mesh, PartitionSpec(data_axis))
        if train_mode_kwarg == "auto":
            # Two conventions in the zoo: `train=True` (BatchNorm models)
            # and `deterministic=False` (Dropout/transformer models);
            # plain models (LeNet) take neither.
            sig = inspect.signature(type(model).__call__)
            if "train" in sig.parameters:
                self._train_kwargs = {"train": True}
            elif "deterministic" in sig.parameters:
                self._train_kwargs = {"deterministic": False}
            else:
                self._train_kwargs = {}
        else:
            self._train_kwargs = (
                {train_mode_kwarg: True} if train_mode_kwarg else {})
        self._donate = donate_state
        self._constrain_state = constrain_state
        #: rematerialize the forward pass in the backward (jax.checkpoint)
        #: — trades ~33% more FLOPs for dropping activation storage, the
        #: standard lever for scaling batch into the HBM ceiling
        #: (SURVEY.md build guidance; TFOS_BENCH_REMAT in bench.py).
        self._remat = remat
        self._jit_step = None  # built lazily: needs init()'s aux-state info

    def _inputs(self, batch):
        if not isinstance(batch, dict):
            return (batch,)
        # Positional binding: only TRAILING keys may be absent — a missing
        # middle key would silently shift later arrays into the wrong
        # model argument (e.g. token_type_ids landing in attention_mask).
        values = []
        missing = None
        for k in self.input_keys:
            if k in batch:
                if missing is not None:
                    raise KeyError(
                        "batch is missing input key {!r} but provides the "
                        "later key {!r}; positional binding would be "
                        "corrupted".format(missing, k))
                values.append(batch[k])
            elif missing is None:
                missing = k
        return tuple(values)

    def _apply(self, params, extra, batch, rngs=None):
        variables = dict(extra)
        variables["params"] = params
        mutable = [k for k in extra.keys()]
        kwargs = dict(self._train_kwargs)
        if rngs:
            kwargs["rngs"] = rngs
        inputs = self._inputs(batch)
        if mutable:
            return self.model.apply(variables, *inputs, mutable=mutable,
                                    **kwargs)
        return self.model.apply(variables, *inputs, **kwargs), {}

    def _build_step(self):
        import jax
        import optax

        def _step(state, batch):
            rngs = None
            if self.dropout_rng:
                rngs = {"dropout": jax.random.fold_in(
                    jax.random.PRNGKey(0), state["step"])}

            apply = jax.checkpoint(self._apply) if self._remat \
                else self._apply

            def loss_of(p):
                # extra/batch/rngs go through checkpoint as ARGUMENTS —
                # closing over them here would make them saved constants
                # of the checkpointed region instead of rematerialized
                logits, new_extra = apply(p, state["extra"], batch, rngs)
                return self.loss_fn(logits, batch), new_extra

            (loss, new_extra), grads = jax.value_and_grad(
                loss_of, has_aux=True)(state["params"])
            updates, opt_state = self.optimizer.update(
                grads, state["opt_state"], state["params"])
            params = optax.apply_updates(state["params"], updates)
            new_state = {"params": params, "extra": new_extra,
                         "opt_state": opt_state, "step": state["step"] + 1}
            return new_state, {"loss": loss}

        # Sharding-annotated jit: XLA inserts the gradient all-reduce over
        # the data axis because batch inputs are split and params/outputs
        # are required replicated. With constrain_state=False (TP/hybrid
        # runs) the state keeps whatever layout the caller placed it in
        # (e.g. megatron rules from parallel/sharding.py) and the step
        # preserves it.
        if self._constrain_state:
            state_in, state_out = self.replicated, self.replicated
            metrics_out = self.replicated
            out_shardings = (state_out, metrics_out)
        else:
            state_in, out_shardings = None, None
        self._jit_step = jax.jit(
            _step,
            in_shardings=(state_in, self.batch_sharding),
            out_shardings=out_shardings,
            donate_argnums=(0,) if self._donate else ())

    def init(self, rng, sample):
        """Replicated train state: {params, extra, opt_state, step}.

        ``sample``: an input array, or a batch dict read via
        ``input_keys``. ``extra`` holds non-param variable collections
        (e.g. BatchNorm's ``batch_stats``) threaded through the step as
        explicit state — the functional analog of TF's stateful update ops.
        """
        import jax
        import jax.numpy as jnp

        inputs = tuple(jnp.asarray(x) for x in self._inputs(sample))
        rngs = {"params": rng}
        if self.dropout_rng:
            rngs["dropout"] = jax.random.fold_in(rng, 1)

        def _init(rngs):
            variables = self.model.init(rngs, *inputs)
            params = variables.pop("params")
            return {"params": params, "extra": dict(variables),
                    "opt_state": self.optimizer.init(params),
                    "step": jnp.zeros((), dtype=jnp.int32)}

        return jax.jit(_init, out_shardings=self.replicated)(rngs)

    def step(self, state, batch):
        """One jitted DP step; batch must be sharded/shardable over data."""
        if self._jit_step is None:
            self._build_step()
        return self._jit_step(state, batch)

    def train_loop(self, state, batches, log_every=50, hooks=(),
                   ledger=None):
        """Drive steps over an (already device-put) batch iterator.

        Returns (state, total_steps, examples/sec). ``hooks``: callables
        ``(step_no, state, metrics) -> None`` (checkpointing, tensorboard).

        Goodput accounting (goodput.py): each step-call window is
        charged to the ledger as ``productive_step`` — the FIRST of a
        process's life as ``compile`` (that call traces and compiles;
        the jitted cache is warm afterwards) — and mirrored into the
        flight recorder as a ``train_step``/``compile`` span, so
        ``scripts/trace_dump.py`` renders a training-run timeline.
        Attribution note for async dispatch: donated buffers make step
        call N+1 block until step N's device work completes, so
        successive call windows cover device time without any extra
        ``block_until_ready`` (which would serialize the pipeline —
        the accounting must never cost the throughput it measures).
        ``ledger=None`` charges the process-global ledger (the one the
        DataFeed's BEAT snapshot carries to the driver); pass
        ``ledger=False`` to opt out. A CUSTOM ledger receives ONLY this
        loop's step envelopes — the framework's inner hooks (checkpoint
        saves/restores, feed waits) always charge ``goodput.ledger()``,
        so full sum-to-wall accounting holds on the process-global
        ledger, not a custom one; custom ledgers are for isolated
        measurement (tests, demos) of the loop itself.
        """
        import jax

        from tensorflowonspark_tpu import goodput
        if ledger is None:
            ledger = goodput.ledger()
        n = 0
        examples = 0
        t0 = time.monotonic()
        metrics = None
        for batch in batches:
            if ledger:
                with ledger.step_span():
                    state, metrics = self.step(state, batch)
            else:
                state, metrics = self.step(state, batch)
            n += 1
            examples += _batch_size(batch)
            for hook in hooks:
                hook(n, state, metrics)
            if log_every and n % log_every == 0:
                jax.block_until_ready(metrics["loss"])
                dt = time.monotonic() - t0
                logger.info("step %d loss %.4f (%.1f ex/s)", n,
                            float(metrics["loss"]), examples / dt)
        if metrics is not None:
            jax.block_until_ready(metrics["loss"])
        dt = max(time.monotonic() - t0, 1e-9)
        return state, n, examples / dt


def _batch_size(batch):
    if isinstance(batch, dict):
        batch = next(iter(batch.values()))
    return batch.shape[0]
