"""Executor process: connects back to the driver and runs tasks serially.

The analog of a 1-core Spark executor + pyspark worker rolled into one
long-lived process. Serial task execution is a *feature* the cluster layer
relies on (as the reference relies on 1-task-slot executors): the node
bootstrap task spawns the trainer subprocess and returns, then feed /
shutdown tasks run on the same executor and find its state via module
globals (the reference's equivalent: executor_id file + TFManager reconnect,
SURVEY.md §3.2 ``_get_manager``).

Runs either spawned-by-driver (local mode) or standalone on a remote host:

    python -m tensorflowonspark_tpu.engine.executor \
        --driver HOST:PORT --executor-id N --authkey-file F --work-dir D

This process must never initialize JAX — the trainer subprocess it spawns
owns the TPU (SURVEY.md §7.3 "Background process + libtpu").
"""

import argparse
import logging
import os
import sys
import traceback
from multiprocessing.connection import Client as ConnClient

from tensorflowonspark_tpu.engine import serializer

logger = logging.getLogger(__name__)

#: Set once at startup; read by the node runtime (node.py) to learn which
#: executor a task is running on. {"executor_id", "work_dir", "host"}
EXECUTOR_INFO = {}


def get_executor_info():
    return dict(EXECUTOR_INFO)


def run_task(func_bytes, payload_bytes):
    """Execute one task; returns a reply dict (never raises)."""
    try:
        func = serializer.loads(func_bytes)
        payload = serializer.loads(payload_bytes) if payload_bytes is not None else None
        value = func(iter(payload) if payload is not None else iter(()))
        if hasattr(value, "__next__") or (hasattr(value, "__iter__")
                                          and not isinstance(value, (list, tuple, dict, str, bytes))):
            value = list(value)
        return {"ok": True, "value": serializer.dumps(value)}
    except BaseException as e:  # noqa: BLE001 - must reach the driver
        tb = traceback.format_exc()
        logger.error("task failed:\n%s", tb)
        return {"ok": False, "error": "{}: {}".format(type(e).__name__, e),
                "traceback": tb}


def executor_main(driver_addr, executor_id, authkey, work_dir):
    os.makedirs(work_dir, exist_ok=True)
    os.chdir(work_dir)
    from tensorflowonspark_tpu import util
    util.write_executor_id(executor_id)
    import multiprocessing
    multiprocessing.current_process().authkey = authkey

    host = util.get_ip_address()
    EXECUTOR_INFO.update(executor_id=executor_id, work_dir=work_dir, host=host)

    conn = ConnClient(tuple(driver_addr), authkey=authkey)
    conn.send({"type": "hello", "executor_id": executor_id, "host": host,
               "pid": os.getpid(), "work_dir": work_dir})
    logger.info("executor %d connected to driver %s", executor_id, driver_addr)

    while True:
        msg = conn.recv()
        mtype = msg.get("type")
        if mtype == "task":
            reply = run_task(msg["func"], msg.get("payload"))
            reply.update(type="result", job_id=msg["job_id"], task_id=msg["task_id"])
            conn.send(reply)
        elif mtype == "stop":
            logger.info("executor %d stopping", executor_id)
            conn.send({"type": "bye", "executor_id": executor_id})
            break
        else:
            logger.warning("executor %d: unknown message %r", executor_id, mtype)
    conn.close()


def main(argv=None):
    parser = argparse.ArgumentParser(description="tensorflowonspark_tpu executor")
    parser.add_argument("--driver", required=True, help="driver HOST:PORT")
    parser.add_argument("--executor-id", type=int, required=True)
    parser.add_argument("--authkey-file", required=True,
                        help="file holding the cluster authkey bytes")
    parser.add_argument("--work-dir", required=True)
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=args.log_level,
        format="%(asctime)s %(levelname)s exec[{}] %(name)s: %(message)s".format(
            args.executor_id))
    # kill -USR1 <executor pid> dumps every thread's stack to the log —
    # the first tool to reach for when a feed wedges on a remote host
    import faulthandler
    import signal as _signal
    faulthandler.register(_signal.SIGUSR1, file=sys.stderr)
    host, port = args.driver.rsplit(":", 1)
    with open(args.authkey_file, "rb") as f:
        authkey = f.read()
    executor_main((host, int(port)), args.executor_id, authkey, args.work_dir)


if __name__ == "__main__":
    # Run the *canonical* module's main: under ``python -m`` this file is
    # the __main__ module, a different object from
    # tensorflowonspark_tpu.engine.executor — task closures importing the
    # latter must see the EXECUTOR_INFO this process populates.
    from tensorflowonspark_tpu.engine.executor import main as _canonical_main

    _canonical_main()
