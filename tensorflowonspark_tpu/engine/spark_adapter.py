"""Optional pyspark adapter: run the cluster API on a real Spark engine.

SURVEY.md §7.3 ("No pyspark in env") keeps the first-party engine
(engine/context.py) as the default execution substrate but owes a thin
shim "so spark-submit parity can be demonstrated". This is that shim:
it wraps a live ``pyspark.SparkContext`` in the exact contract
``cluster.run`` / ``TFCluster`` consume from the first-party engine —

    sc.parallelize(data, num_slices)  -> RDD
    sc.union([rdds])                  -> RDD
    sc.defaultParallelism
    rdd.mapPartitions(f) / .foreachPartition(f)
    rdd.foreachPartitionAsync(f, one_task_per_executor=) -> result.get()
    rdd.union / .getNumPartitions / .collect / .count

so a reference program's ``spark-submit`` launch path works by passing
``SparkEngineAdapter(spark_context)`` wherever the engine ``Context``
would go (reference: ``TFCluster.run(sc, ...)`` took the real
SparkContext directly).

Placement notes, same constraints the reference documented for
TFoS-on-Spark:

- Run with one task slot per executor (``spark.executor.cores`` ==
  ``spark.task.cpus``) so the ``num_executors`` bootstrap tasks land on
  distinct executors. PySpark has no placement API; the reference
  relied on exactly this configuration, and so does the shim
  (``one_task_per_executor`` is accepted and honored *by partition
  count*, the same mechanism ``TFSparkNode.run`` used).
- Pass ``manager_mode="remote"`` to ``cluster.run`` so each node's
  queue broker binds its routable IP instead of loopback — Spark may
  schedule feed tasks on any executor.
- pyspark's RDD API has no async job submission, so
  ``foreachPartitionAsync`` runs the blocking ``foreachPartition`` on a
  driver-side thread (exactly how the reference's TFCluster kept the
  bootstrap job running behind the barrier).

This module imports pyspark lazily: the framework never requires it.
"""

import logging
import threading

logger = logging.getLogger(__name__)


class SparkAsyncResult(object):
    """`AsyncResult.get(timeout)`-shaped handle over a driver thread."""

    def __init__(self, fn):
        self._error = None
        self._done = threading.Event()

        def runner():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - re-raised in get()
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(  # tfos: unjoined(get() waits on the done Event instead; the daemon thread ends with fn())
            target=runner, name="spark-adapter-job", daemon=True)
        self._thread.start()

    def get(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                "spark job still running after {}s".format(timeout))
        if self._error is not None:
            raise self._error
        return None

    def ready(self):
        return self._done.is_set()

    def done(self):
        return self._done.is_set()

    def successful(self):
        return self._done.is_set() and self._error is None

    def first_error(self):
        """(task_id, error) like the engine's AsyncResult; pyspark gives
        no per-task attribution, so the job's error maps to task 0."""
        return (0, self._error) if self._error is not None else None


class SparkRDDAdapter(object):
    """First-party-RDD surface over a pyspark RDD."""

    def __init__(self, engine, rdd):
        self.ctx = engine
        self._rdd = rdd

    # -- the contract cluster.py / examples consume ----------------------

    def mapPartitions(self, f):
        return SparkRDDAdapter(self.ctx, self._rdd.mapPartitions(f))

    def map(self, f):
        return SparkRDDAdapter(self.ctx, self._rdd.map(f))

    def union(self, other):
        other_rdd = other._rdd if isinstance(other, SparkRDDAdapter) else other
        return SparkRDDAdapter(self.ctx, self._rdd.union(other_rdd))

    def getNumPartitions(self):
        return self._rdd.getNumPartitions()

    def collect(self):
        return self._rdd.collect()

    def count(self):
        return self._rdd.count()

    def take(self, n):
        return self._rdd.take(n)

    def foreachPartition(self, f):
        self.foreachPartitionAsync(f).get()

    def foreachPartitionAsync(self, f, one_task_per_executor=False,
                              fail_fast=True):
        """Async partition job; see module docstring for the placement
        contract behind ``one_task_per_executor``."""
        del one_task_per_executor  # honored by partition count + spark conf

        if fail_fast:
            # Spark's native semantics already abort the job on a failed
            # task (after task retries), which is exactly fail-fast.
            def run_and_discard(it, _f=f):
                _f(it)
                return iter(())

            rdd = self._rdd.mapPartitions(run_and_discard)
            # pyspark evaluates lazily: count() is the canonical cheap
            # action that forces every partition exactly once
            return SparkAsyncResult(rdd.count)

        # fail_fast=False (cleanup jobs: EndFeed must reach EVERY
        # executor): a raising task would make Spark cancel the stage's
        # remaining tasks, so no task may ever raise — each partition
        # catches its own error and returns it as data; the collected
        # errors re-raise on the driver after all partitions ran.
        #
        # Deliberate no-retry tradeoff: returning the error as data also
        # OPTS OUT of Spark's native task retry, so a transiently
        # failing cleanup partition runs exactly once — less delivery
        # assurance than Spark's default for transient faults. In-task
        # retries cannot fix this safely: the partition iterator cannot
        # be rewound (a replay would feed a truncated partition), a
        # "consumed nothing yet" guard races fns that hand the iterator
        # to a background thread (node._inference's feeder — a zombie
        # feeder from attempt 1 can steal records from attempt 2 or
        # trip 'generator already executing'), and the framework's own
        # fail_fast=False task (node.shutdown) drains its iterator as
        # its first statement so it could never qualify anyway. Callers
        # needing stronger cleanup delivery should make the cleanup
        # idempotent and resubmit the job.
        def run_catching(it, _f=f):
            try:
                _f(it)
                return iter(())
            except Exception:  # noqa: BLE001 - re-raised collected below
                import traceback
                return iter([traceback.format_exc()])

        rdd = self._rdd.mapPartitions(run_catching)

        def collect_then_raise(_rdd=rdd):
            errors = _rdd.collect()
            if errors:
                raise RuntimeError(
                    "{} partition task(s) failed; first:\n{}".format(
                        len(errors), errors[0]))

        return SparkAsyncResult(collect_then_raise)


class SparkEngineAdapter(object):
    """Engine-``Context``-shaped adapter over a ``pyspark.SparkContext``.

    ``num_executors`` is what ``cluster.run(sc, ..., num_executors=N)``
    should be called with; when not given it falls back to
    ``sc.defaultParallelism`` (the reference's own convention for local
    runs).
    """

    def __init__(self, spark_context, num_executors=None):
        self._sc = spark_context
        self.num_executors = int(num_executors or
                                 spark_context.defaultParallelism)

    @property
    def defaultParallelism(self):
        return self._sc.defaultParallelism

    def parallelize(self, data, num_slices=None):
        return SparkRDDAdapter(
            self, self._sc.parallelize(list(data),
                                       num_slices or self.num_executors))

    def union(self, rdds):
        # flat SparkContext.union, not pairwise chaining: K-deep nested
        # UnionRDD lineage (sc.union([rdd] * epochs) in cluster.train)
        # risks StackOverflowError serializing the DAG on real Spark
        if all(isinstance(r, SparkRDDAdapter) for r in rdds):
            return SparkRDDAdapter(
                self, self._sc.union([r._rdd for r in rdds]))
        out = rdds[0]
        for r in rdds[1:]:
            out = out.union(r)
        return out

    def stop(self):
        """No-op: the SparkContext's lifecycle belongs to the caller
        (spark-submit / SparkSession), not to the framework."""

    def __repr__(self):
        return "SparkEngineAdapter({!r}, num_executors={})".format(
            self._sc, self.num_executors)


def from_spark(spark_context=None, num_executors=None):
    """Build an adapter; with no argument, attach to the active context.

    The zero-argument form is the spark-submit path::

        from tensorflowonspark_tpu.engine import spark_adapter
        sc = spark_adapter.from_spark()        # active SparkContext
        cluster.run(sc, map_fun, args, sc.num_executors,
                    manager_mode="remote", ...)
    """
    if spark_context is None:
        import pyspark
        spark_context = pyspark.SparkContext.getOrCreate()
    return SparkEngineAdapter(spark_context, num_executors)
