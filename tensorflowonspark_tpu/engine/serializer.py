"""Closure/task serialization for the engine.

Spark ships task closures with cloudpickle; so do we (cloudpickle 3.x is
in the image). Payloads travel only over authkey-authenticated
``multiprocessing.connection`` channels between our own driver and
executors — the same trust model as Spark's closure plane.
"""

import cloudpickle


def dumps(obj):
    return cloudpickle.dumps(obj)


def loads(data):
    return cloudpickle.loads(data)
