"""Micro-batch streaming: the Spark Streaming DStream analog.

Reference capability (SURVEY.md §2 Cluster API row): ``TFCluster.run``
"also supports Spark Streaming DStreams" — continuous feeding where each
micro-batch RDD is pushed through the same queue plane, and
``shutdown(ssc)`` stops the stream first (§3.5).

Shape kept deliberately Spark-like::

    ssc = StreamingContext(sc, batch_interval=1.0)
    stream = ssc.queueStream(rdd_queue)        # or .textFileStream(dir)
    stream.foreachRDD(lambda rdd: cluster.train(rdd))
    ssc.start(); ...; cluster.shutdown(ssc)
"""

import logging
import os
import queue as _queue
import threading
import time

logger = logging.getLogger(__name__)


class DStream(object):
    """A stream of RDDs delivered to registered callbacks per interval."""

    def __init__(self, ssc):
        self.ssc = ssc
        self._actions = []

    def foreachRDD(self, fn):
        """Register ``fn(rdd)`` to run on every micro-batch."""
        self._actions.append(fn)
        return self

    def _dispatch(self, rdd):
        for fn in self._actions:
            fn(rdd)


class _QueueStream(DStream):
    def __init__(self, ssc, rdd_queue):
        super(_QueueStream, self).__init__(ssc)
        self._queue = rdd_queue

    def _poll(self):
        try:
            return self._queue.get_nowait()
        except _queue.Empty:
            return None


class _TextFileStream(DStream):
    """Watches a directory; new files become line-RDDs (one per batch)."""

    def __init__(self, ssc, directory, num_slices=None):
        super(_TextFileStream, self).__init__(ssc)
        self.directory = directory
        self.num_slices = num_slices
        self._seen = set(os.listdir(directory)) if os.path.isdir(directory) \
            else set()

    def _poll(self):
        if not os.path.isdir(self.directory):
            return None
        # Hidden files are invisible, exactly as Spark's textFileStream
        # treats them: writers land data atomically by writing
        # ".name.tmp" in-place then renaming — a poll must never read a
        # half-written file.
        new = sorted(n for n in
                     set(os.listdir(self.directory)) - self._seen
                     if not n.startswith("."))
        if not new:
            return None
        self._seen.update(new)
        lines = []
        for name in new:
            with open(os.path.join(self.directory, name)) as f:
                lines.extend(f.read().splitlines())
        return self.ssc.sc.parallelize(lines, self.num_slices)


class StreamingContext(object):
    """Driver-side micro-batch scheduler over the engine context."""

    def __init__(self, sc, batch_interval=1.0):
        self.sc = sc
        self.batch_interval = batch_interval
        self._streams = []
        self._thread = None
        self._stop = threading.Event()
        self._error = None

    def queueStream(self, rdds):
        """Stream draining a queue.Queue of RDDs (or a prefilled list)."""
        q = rdds
        if isinstance(rdds, (list, tuple)):
            q = _queue.Queue()
            for r in rdds:
                q.put(r)
        stream = _QueueStream(self, q)
        self._streams.append(stream)
        return stream

    def textFileStream(self, directory, num_slices=None):
        stream = _TextFileStream(self, directory, num_slices)
        self._streams.append(stream)
        return stream

    def start(self):
        def _loop():
            try:
                while not self._stop.is_set():
                    t0 = time.monotonic()
                    for stream in self._streams:
                        rdd = stream._poll()
                        if rdd is not None:
                            stream._dispatch(rdd)
                    left = self.batch_interval - (time.monotonic() - t0)
                    if left > 0:
                        self._stop.wait(left)
            except BaseException as e:  # noqa: BLE001 - surfaced on stop
                logger.error("streaming loop failed", exc_info=True)
                self._error = e

        self._thread = threading.Thread(target=_loop, name="streaming-loop",
                                        daemon=True)
        self._thread.start()

    def awaitTermination(self, timeout=None):
        self._thread.join(timeout)

    def stop(self, drain=True):
        """Stop the loop; with ``drain`` run one final poll so queued
        micro-batches aren't dropped. Re-raises a loop error if one hit."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if drain and self._error is None:
            for stream in self._streams:
                while True:
                    rdd = stream._poll()
                    if rdd is None:
                        break
                    stream._dispatch(rdd)
        if self._error is not None:
            raise RuntimeError("streaming loop failed") from self._error
