"""Minimal Spark-shaped DataFrame: named, typed columns over an RDD of rows.

The reference's pipeline/dfutil layers consume Spark DataFrames; pyspark
isn't in the image (SURVEY.md §7 environment note), so the engine carries
a small columnar shim with the same *shape*: a row RDD plus a schema, and
the handful of operations the framework layers exercise (``rdd``,
``select``, ``withColumn``, ``collect``, ``count``, ``columns``). Rows are
plain dicts — the pipeline's input_mapping/output_mapping address columns
by name exactly as the reference does.
"""

import numpy as np


#: schema dtype vocabulary (mirrors the subset dfutil round-trips)
DTYPES = ("int64", "float32", "string", "binary",
          "array<int64>", "array<float32>", "array<string>", "array<binary>")


def _infer_dtype(value):
    if isinstance(value, (list, tuple, np.ndarray)):
        if len(value) == 0:
            return "array<float32>"
        inner = _infer_dtype(value[0])
        return "array<{}>".format(inner)
    if isinstance(value, (bool, int, np.integer)):
        return "int64"
    if isinstance(value, (float, np.floating)):
        return "float32"
    if isinstance(value, str):
        return "string"
    if isinstance(value, (bytes, bytearray)):
        return "binary"
    raise TypeError("cannot infer dtype for {!r}".format(type(value)))


def infer_schema_from_row(row):
    """{col: value} -> ordered [(name, dtype)] (sorted for determinism)."""
    return [(name, _infer_dtype(row[name])) for name in sorted(row)]


class DataFrame(object):
    """A row RDD + schema. Construct via ``Context.createDataFrame``.

    ``schema`` may be a ``[(name, dtype)]`` list or a zero-arg callable
    returning one — the callable is resolved on first access, so a
    producer whose dtypes are only knowable by computing data (e.g.
    ``TFModel.transform``) can stay lazy.
    """

    def __init__(self, rdd, schema):
        self.rdd = rdd
        self._schema = None if callable(schema) else list(schema)
        self._schema_fn = schema if callable(schema) else None

    @property
    def schema(self):
        if self._schema is None:
            self._schema = list(self._schema_fn())
        return self._schema

    @property
    def columns(self):
        return [name for name, _ in self.schema]

    def dtype_of(self, col):
        for name, dtype in self.schema:
            if name == col:
                return dtype
        raise KeyError(col)

    def select(self, *cols):
        cols = list(cols)
        schema = [(n, d) for n, d in self.schema if n in cols]
        missing = set(cols) - {n for n, _ in schema}
        if missing:
            raise KeyError("no such columns: {}".format(sorted(missing)))
        rdd = self.rdd.map(lambda row, _c=tuple(cols): {k: row[k] for k in _c})
        return DataFrame(rdd, schema)

    def withColumn(self, name, fn, dtype):
        """Add/replace a column computed per row by ``fn(row)``."""
        def add(row, _fn=fn, _n=name):
            out = dict(row)
            out[_n] = _fn(row)
            return out
        schema = [(n, d) for n, d in self.schema if n != name]
        schema.append((name, dtype))
        return DataFrame(self.rdd.map(add), schema)

    def filter(self, predicate):
        """Rows where ``predicate(row)`` is truthy; schema unchanged.

        ``predicate`` is a plain python fn over the row dict (the
        ``withColumn`` convention — no expression DSL exists here).
        """
        return DataFrame(self.rdd.filter(predicate), self.schema)

    #: Spark alias: ``where`` is ``filter``
    where = filter

    def drop(self, *cols):
        """Drop the named columns (unknown names ignored, like Spark).

        Dropping everything is refused — a zero-column DataFrame has no
        row representation here (rows are plain dicts).
        """
        cols = set(cols)
        keep = [n for n, _ in self.schema if n not in cols]
        if not keep:
            raise ValueError("drop() would remove every column")
        if len(keep) == len(self.schema):
            return self
        return self.select(*keep)

    def collect(self):
        return self.rdd.collect()

    def count(self):
        return self.rdd.count()

    def getNumPartitions(self):
        return self.rdd.getNumPartitions()

    def repartition(self, n):
        return DataFrame(self.rdd.repartition(n), self.schema)


def create_dataframe(ctx, data, schema=None, num_slices=None):
    """rows (dicts, or tuples + column-name schema) -> DataFrame.

    ``schema``: [(name, dtype)] or [name, ...] (dtypes inferred) or None
    (rows must be dicts; schema inferred from the first row).
    """
    data = list(data)
    if not data:
        raise ValueError("cannot create DataFrame from empty data")
    first = data[0]
    if schema is None:
        if not isinstance(first, dict):
            raise ValueError("schema required for non-dict rows")
        schema = infer_schema_from_row(first)
    elif schema and not isinstance(schema[0], (list, tuple)):
        names = list(schema)
        if isinstance(first, dict):
            schema = [(n, _infer_dtype(first[n])) for n in names]
        else:
            schema = [(n, _infer_dtype(v)) for n, v in zip(names, first)]
            data = [dict(zip(names, row)) for row in data]
    elif not isinstance(first, dict):
        names = [n for n, _ in schema]
        data = [dict(zip(names, row)) for row in data]
    return DataFrame(ctx.parallelize(data, num_slices), schema)
