"""Driver context: owns executors, schedules tasks, surfaces errors.

The Spark-role substrate (SURVEY.md §1 "load-bearing third-party
substrate"): process placement, task dispatch, and error aggregation for
the cluster layer above. Local mode spawns executor processes itself;
standalone mode (``spawn_local=False``) just listens and lets a launcher
start ``python -m tensorflowonspark_tpu.engine.executor`` on each host —
the ``spark-submit``-shaped path.

Deliberate semantic carried over from Spark: a failed task fails the job
and the error (with the executor-side traceback) re-raises on the driver
when the job result is awaited — the reference's error-propagation story
(SURVEY.md §3.5) depends on exactly this.
"""

import logging
import os
import queue
import subprocess
import sys
import threading
import time
from multiprocessing.connection import Listener

from tensorflowonspark_tpu.engine import serializer
from tensorflowonspark_tpu.engine.rdd import RDD, _Partition

logger = logging.getLogger(__name__)

_STOP = object()


class TaskError(RuntimeError):
    """A task failed on an executor; message carries the remote traceback."""


class AsyncResult(object):
    """Handle to a running job (analog of Spark's ASyncRDDActions result)."""

    def __init__(self, num_tasks, fail_fast=True):
        self._results = [None] * num_tasks
        self._pending = num_tasks
        self._errors = []
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._fail_fast = fail_fast
        # ``fail_fast=False`` keeps run-every-task semantics for jobs whose
        # siblings matter even after one fails — cleanup/shutdown jobs
        # (EndFeed to executor k must still be delivered when executor j's
        # shutdown task raised).
        # Set on the FIRST failure, while other tasks may still be running:
        # the job is already lost (failed-task-fails-the-job), so waiters
        # must not keep blocking on tasks whose only remaining purpose is
        # to time out. Observed on-chip (round 5, window 2): a trainer
        # wedged in a C-level PJRT compile made every later feed task burn
        # its full 600s feed_timeout before the driver heard about the
        # task-1 failure it had been holding for half an hour.
        self._failed = threading.Event()
        # _wake fires at either terminal event (all tasks resolved, or
        # first failure of a fail-fast job) so get() is one blocking wait,
        # not a poll — the bootstrap job is awaited for days at a time.
        self._wake = threading.Event()

    def _complete(self, task_id, value):
        with self._lock:
            self._results[task_id] = value
            self._pending -= 1
            if self._pending == 0:
                self._done.set()
                self._wake.set()

    def _fail(self, task_id, error):
        with self._lock:
            self._errors.append((task_id, error))
            self._pending -= 1
            if self._pending == 0:
                self._done.set()
        if self._fail_fast:
            self._failed.set()
        if self._fail_fast or self._done.is_set():
            self._wake.set()

    def done(self):
        return self._done.is_set()

    def successful(self):
        return self._done.is_set() and not self._errors

    def first_error(self):
        """(task_id, error) of the first failed task so far, else None —
        readable while other tasks are still running (fail-fast probes)."""
        with self._lock:
            return self._errors[0] if self._errors else None

    def get(self, timeout=None):
        """Block until the job completes OR its first task fails.

        Fail-fast is the Spark-parity contract: one failed task aborts the
        job, so the driver re-raises the moment the first error arrives
        rather than waiting out tasks that are already doomed (undispatched
        tasks of a failed job are skipped by the dispatch loop). Tasks
        still running when this raises are bounded by ``Context.stop``'s
        terminate-with-escalation."""
        if not self._wake.wait(timeout):
            raise TimeoutError(
                "job did not complete within {}s".format(timeout))
        if self._errors:
            task_id, error = self._errors[0]
            raise TaskError("task {} failed: {}".format(task_id, error))
        return list(self._results)


class _ExecutorHandle(object):
    """Driver-side mirror of one executor: its connection + dispatch thread."""

    def __init__(self, ctx, conn, meta):
        self.ctx = ctx
        self.conn = conn
        self.executor_id = meta["executor_id"]
        self.meta = meta
        self.own_queue = queue.Queue()
        self.alive = True
        self.conn_broken = False
        self.thread = threading.Thread(  # tfos: unjoined(daemon; exits when its executor connection closes — the engine has no per-handle teardown hook)
            target=self._loop, name="executor-handle-%d" % self.executor_id,
            daemon=True)
        self.thread.start()

    def _next_task(self):
        """Prefer pinned tasks, else pull from the shared pool."""
        while self.alive and not self.ctx._stopping.is_set():
            try:
                return self.own_queue.get(timeout=0.05)
            except queue.Empty:
                pass
            try:
                return self.ctx._shared_tasks.get(timeout=0.05)
            except queue.Empty:
                continue
        return _STOP

    def _loop(self):
        task = None
        try:
            while True:
                task = self._next_task()
                if task is _STOP:
                    break
                if task["result"]._failed.is_set():
                    # Job already lost: don't ship a task whose only
                    # possible outcome is burning its own timeout (e.g. a
                    # feed task pushing 600s into a ring nobody drains).
                    # Checked BEFORE the exclusion below, so an excluded
                    # executor drains a dead job's tasks instead of
                    # requeueing them forever (with every eligible
                    # sibling dead, nobody else ever would).
                    task["result"]._fail(
                        task["task_id"],
                        "job aborted: an earlier task already failed")
                    task = None
                    continue
                exclude = task.get("exclude")
                if exclude and self.executor_id in exclude:
                    # blacklisted for this job (supervision plane): hand
                    # the task back for an eligible sibling; the short
                    # sleep keeps an idle excluded executor from spinning
                    # on its own requeue
                    self.ctx._shared_tasks.put(task)
                    time.sleep(0.02)
                    task = None
                    continue
                self.conn.send({"type": "task", "job_id": task["job_id"],
                                "task_id": task["task_id"], "func": task["func"],
                                "payload": task["payload"]})
                reply = self.conn.recv()
                result = task["result"]
                if reply.get("ok"):
                    result._complete(task["task_id"],
                                     serializer.loads(reply["value"]))
                else:
                    self.ctx._saw_failure = True
                    result._fail(task["task_id"],
                                 reply.get("traceback") or reply.get("error"))
                task = None
        except (EOFError, OSError, BrokenPipeError) as e:
            logger.error("executor %d connection lost: %s", self.executor_id, e)
            self.ctx._saw_failure = True
            self.conn_broken = True
            if task is not None and task is not _STOP:
                task["result"]._fail(
                    task["task_id"],
                    "executor {} died while running task (connection lost: {})"
                    .format(self.executor_id, e))
            self.alive = False
            self.ctx._on_handle_dead(self)
        finally:
            self.alive = False

    def send_stop(self):
        self.own_queue.put(_STOP)

    def close(self):
        try:
            if not self.conn_broken:
                self.conn.send({"type": "stop"})
                # Only await the bye reply if our dispatch thread has exited:
                # a Connection must not be recv()'d from two threads, and a
                # still-alive thread may be blocked in recv on a long task.
                if not self.thread.is_alive() and self.conn.poll(5):
                    self.conn.recv()
        except (EOFError, OSError, BrokenPipeError):
            pass
        try:
            self.conn.close()
        except OSError:
            pass


class Context(object):
    """Driver entry point (the ``sc`` the cluster API takes).

    Args:
      num_executors: world size (fixed, like the reference's).
      spawn_local: spawn executor subprocesses on this host (local mode);
        False = standalone mode, wait for externally launched executors.
      executor_env: extra env vars for spawned executors.
      work_root: scratch root; each executor gets work_root/executor-N as
        its cwd (the executor-id persistence dir, SURVEY.md util row).
      host: address to listen on (default loopback — local mode).
    """

    def __init__(self, num_executors, spawn_local=True, executor_env=None,
                 work_root=None, host="127.0.0.1", app_name="tfos-tpu",
                 start_timeout=120):
        self.num_executors = num_executors
        self.app_name = app_name
        self.authkey = os.urandom(20)
        # Auto-generated work roots are cleaned up on a CLEAN stop();
        # any failure keeps them — executor.log is the post-mortem. A
        # user-passed work_root is never deleted (it's theirs), and
        # TFOS_KEEP_WORKDIR=1 keeps even auto roots (debug sessions).
        self._auto_work_root = work_root is None
        self._saw_failure = False
        self.work_root = work_root or os.path.join(
            os.getcwd(), ".tfos-{}-{}".format(app_name, os.getpid()))
        os.makedirs(self.work_root, exist_ok=True)
        # backlog: mp.Listener defaults to 1, and a pod-shaped fleet
        # connects all at once — overflowed SYNs leave clients half-open
        # (ESTAB on their side, nothing in our accept queue) wedged in
        # the authkey challenge recv forever (found by the 8-process
        # scale rehearsal; 5/8 or 7/8 would connect, never all)
        self._listener = Listener((host, 0), backlog=128,
                                  authkey=self.authkey)
        self.driver_addr = self._listener.address
        self._handles = {}
        self._procs = []
        self._shared_tasks = queue.Queue()
        self._stopping = threading.Event()
        self._job_counter = 0
        self._lock = threading.Lock()
        # tfos: unjoined(daemon; _accept_loop exits when stop() closes the listening socket)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="engine-accept", daemon=True)
        self._accept_thread.start()
        # Connection info lands on disk BEFORE we block waiting for
        # executors, so standalone-mode launchers can read it and start
        # `python -m tensorflowonspark_tpu.engine.executor` on each host.
        self.authkey_file = self._write_connection_info()
        self._spawn_local = spawn_local
        self._executor_env = dict(executor_env or {})
        if spawn_local:
            self._spawn_local_executors(self._executor_env)
        self._await_executors(start_timeout)

    # -- bootstrap -------------------------------------------------------

    def _write_connection_info(self):
        """Write authkey (0600) + driver.info JSON; returns authkey path."""
        import json
        authkey_file = os.path.join(self.work_root, "authkey")
        fd = os.open(authkey_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(self.authkey)
        with open(os.path.join(self.work_root, "driver.info"), "w") as f:
            json.dump({"host": self.driver_addr[0], "port": self.driver_addr[1],
                       "authkey_file": authkey_file,
                       "num_executors": self.num_executors}, f)
        return authkey_file

    def _spawn_local_executors(self, executor_env):
        for i in range(self.num_executors):
            self._spawn_one(i, executor_env)
        logger.info("spawned %d local executors (logs under %s)",
                    self.num_executors, self.work_root)

    def _spawn_one(self, executor_id, executor_env=None):
        """Spawn one local executor process; returns the Popen handle."""
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env.update(executor_env if executor_env is not None
                   else self._executor_env)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        work_dir = os.path.join(self.work_root, "executor-%d" % executor_id)
        os.makedirs(work_dir, exist_ok=True)
        log_path = os.path.join(work_dir, "executor.log")
        logfh = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tensorflowonspark_tpu.engine.executor",
             "--driver", "{}:{}".format(*self.driver_addr),
             "--executor-id", str(executor_id),
             "--authkey-file", self.authkey_file,
             "--work-dir", work_dir],
            env=env, stdout=logfh, stderr=subprocess.STDOUT)
        logfh.close()
        self._procs.append(proc)
        return proc

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                conn = self._listener.accept()
            except Exception as e:  # noqa: BLE001 - incl. AuthenticationError
                if self._stopping.is_set():
                    break
                logger.warning("rejected executor connection: %s", e)
                continue
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            if hello.get("type") != "hello":
                conn.close()
                continue
            eid = hello.get("executor_id")
            with self._lock:
                old = self._handles.get(eid)
                if old is not None and old.alive:
                    logger.error(
                        "duplicate executor_id %s from %s rejected (already "
                        "registered and alive)", eid, hello.get("host"))
                    conn.close()
                    continue
            handle = _ExecutorHandle(self, conn, hello)
            with self._lock:
                self._handles[eid] = handle
            logger.info("executor %d registered from %s (pid %s)",
                        eid, hello.get("host"), hello.get("pid"))

    def _await_executors(self, timeout):
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                n = len(self._handles)
            if n >= self.num_executors:
                return
            for proc in self._procs:
                if proc.poll() is not None:
                    # before stop(): its clean-exit cleanup must not
                    # delete the very logs this error points at
                    self._saw_failure = True
                    self.stop()
                    raise RuntimeError(
                        "executor process exited with code {} during startup; "
                        "see logs under {}".format(proc.returncode, self.work_root))
            if time.monotonic() > deadline:
                self._saw_failure = True
                self.stop()
                raise TimeoutError(
                    "only {}/{} executors connected within {}s".format(
                        n, self.num_executors, timeout))
            time.sleep(0.05)

    # -- Spark-shaped API ------------------------------------------------

    @property
    def defaultParallelism(self):
        return self.num_executors

    def parallelize(self, data, num_slices=None):
        data = list(data)
        n = num_slices or self.num_executors
        n = max(1, min(n, len(data)) if data else 1)
        size, extra = divmod(len(data), n)
        parts, start = [], 0
        for i in range(n):
            end = start + size + (1 if i < extra else 0)
            parts.append(_Partition(data[start:end]))
            start = end
        return RDD(self, parts)

    def createDataFrame(self, data, schema=None, num_slices=None):
        """Rows -> DataFrame (see engine/dataframe.py for row/schema forms)."""
        from tensorflowonspark_tpu.engine.dataframe import create_dataframe
        return create_dataframe(self, data, schema, num_slices)

    def union(self, rdds):
        out = rdds[0]
        for r in rdds[1:]:
            out = out.union(r)
        return out

    def run_job(self, rdd, func, one_task_per_executor=False,
                fail_fast=True, exclude=()):
        """Ship ``func`` over every partition; returns :class:`AsyncResult`.

        ``fail_fast=False`` opts a job out of abort-on-first-failure:
        every task still runs and ``get()`` waits for all of them
        (cleanup/shutdown jobs).

        ``exclude``: executor ids barred from running this job's tasks —
        the supervision plane's blacklist (a repeatedly failing executor
        keeps its process but receives no work). Pinned
        (one_task_per_executor) jobs simply skip excluded executors in
        the task->executor mapping; shared-pool tasks carry the set and
        an excluded executor that pulls one hands it back.

        Fail-fast abort scope (deliberately BEST-EFFORT): the first
        failure wakes ``get()`` immediately and marks the job failed, and
        the dispatch loop skips every not-yet-shipped task of that job —
        but tasks ALREADY shipped to an executor run to completion (or
        burn their own timeout) and their results are discarded. There is
        no in-flight cancel message: the executor protocol is
        send-task/await-reply over one connection, so a cancel could not
        be heard until the task finished anyway — preemption would need
        killing the executor process, which costs more than letting a
        doomed task drain (and the trainer-owned TPU makes process
        recycling expensive). Callers must therefore treat ``get()``
        raising as "job lost", not "cluster quiesced"; ``Context.stop``'s
        terminate-with-escalation is the hard bound on stragglers."""
        partitions = rdd._partitions
        exclude = frozenset(exclude or ())
        result = AsyncResult(len(partitions), fail_fast=fail_fast)
        with self._lock:
            self._job_counter += 1
            job_id = self._job_counter
            handles = {eid: h for eid, h in self._handles.items()
                       if h.alive and eid not in exclude}
        if not handles:
            raise RuntimeError(
                "no executors alive to run job" +
                (" (after excluding {})".format(sorted(exclude))
                 if exclude else ""))
        if one_task_per_executor and len(partitions) > len(handles):
            raise ValueError(
                "job needs {} executors but only {} are alive{}".format(
                    len(partitions), len(handles),
                    " and eligible" if exclude else ""))
        for task_id, part in enumerate(partitions):
            full = _compose(part.transform, func)
            task = {"job_id": job_id, "task_id": task_id,
                    "func": serializer.dumps(full),
                    "payload": serializer.dumps(part.payload),
                    "result": result, "exclude": exclude}
            if one_task_per_executor:
                executor_id = sorted(handles)[task_id]
                handles[executor_id].own_queue.put(task)
            else:
                self._shared_tasks.put(task)
        return result

    def executors_alive(self):
        with self._lock:
            return sorted(eid for eid, h in self._handles.items() if h.alive)

    def revive_executor(self, executor_id, timeout=60):
        """Respawn a dead local executor under its original id — the
        "capacity returns" half of the supervision plane's elastic
        resize (an ElasticResize regrow probe watches
        :meth:`executors_alive` recover). The replacement process
        reuses the executor's work dir and registers through the normal
        accept loop (the duplicate-id guard passes because the old
        handle is dead). Returns False if the executor is already
        alive; raises in standalone mode (the launcher owns process
        placement there) or when the replacement fails to register
        within ``timeout``."""
        executor_id = int(executor_id)
        with self._lock:
            handle = self._handles.get(executor_id)
            if handle is not None and handle.alive:
                return False
        if not self._spawn_local:
            raise NotImplementedError(
                "revive_executor requires local mode; standalone "
                "launchers must restart their own executor processes")
        if self._stopping.is_set():
            raise RuntimeError("context is stopping; not reviving")
        proc = self._spawn_one(executor_id)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                handle = self._handles.get(executor_id)
                if handle is not None and handle.alive:
                    logger.info("executor %d revived (pid %d)",
                                executor_id, proc.pid)
                    return True
            if proc.poll() is not None:
                raise RuntimeError(
                    "revived executor {} exited with code {} during "
                    "startup; see logs under {}".format(
                        executor_id, proc.returncode, self.work_root))
            time.sleep(0.05)
        raise TimeoutError(
            "revived executor {} did not register within {}s".format(
                executor_id, timeout))

    def _on_handle_dead(self, handle):
        """Reap a dead executor: fail its pinned tasks, and if no executors
        remain, fail everything in the shared pool — a job must never hang
        because its worker died (the docstring's failed-task-fails-the-job
        contract)."""
        with self._lock:
            if self._handles.get(handle.executor_id) is handle:
                del self._handles[handle.executor_id]
            any_alive = any(h.alive for h in self._handles.values())
        _drain_failing(handle.own_queue,
                       "executor {} died before running pinned task".format(
                           handle.executor_id))
        if not any_alive and not self._stopping.is_set():
            _drain_failing(self._shared_tasks, "no executors alive")

    def stop(self, timeout=15):
        """Stop executors and the listener; idempotent."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        _drain_failing(self._shared_tasks, "driver stopping")
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            h.send_stop()
        for h in handles:
            h.thread.join(timeout=5)
        for h in handles:
            h.close()
        try:
            self._listener.close()
        except OSError:
            pass
        deadline = time.monotonic() + timeout
        for proc in self._procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                logger.warning("killing unresponsive executor pid %s", proc.pid)
                self._saw_failure = True
                proc.kill()
                proc.wait(timeout=5)
            if proc.returncode not in (0, None):
                self._saw_failure = True
        if self._procs:
            # local executors shared this host: reap any shm feed rings
            # their processes left behind (SIGKILL skips atexit paths).
            # glob first — sweep_stale only loads/builds the native lib
            # at the unlink step, so a queue-only driver with nothing to
            # reap never pays a g++ build (or its failure) at shutdown
            import glob as _glob
            if _glob.glob("/dev/shm/tfos-*.*"):
                try:
                    from tensorflowonspark_tpu import shm
                    shm.sweep_stale()
                except Exception:  # noqa: BLE001 - cleanup is best effort
                    logger.debug("stale ring sweep failed", exc_info=True)
        if (self._auto_work_root and not self._saw_failure
                and os.environ.get("TFOS_KEEP_WORKDIR") != "1"):
            # clean exit: the auto-generated scratch root (executor logs,
            # authkey, driver.info) has served its purpose — don't litter
            # the caller's cwd with one dir per run. Any failure above
            # keeps it: executor.log is the post-mortem.
            self._remove_engine_artifacts()
        elif self._saw_failure:
            logger.info("keeping work root %s (failures this session)",
                        self.work_root)

    def _remove_engine_artifacts(self):
        """Remove only what the engine itself created under work_root.

        Executors ``os.chdir`` into ``work_root/executor-N``, so user
        task files written with relative paths (without
        ``ctx.absolute_path``) land there — an ``shutil.rmtree`` of the
        whole root on a clean run silently destroyed them. The engine's
        own artifacts are precisely enumerable (authkey, driver.info,
        each executor's executor.log + persisted executor_id), so remove
        exactly those; directories are removed only once empty, and a
        root still holding user files survives intact.
        """
        from tensorflowonspark_tpu.util import EXECUTOR_ID_FILE
        for name in ("authkey", "driver.info"):
            try:
                os.unlink(os.path.join(self.work_root, name))
            except OSError:
                pass
        for i in range(self.num_executors):
            exec_dir = os.path.join(self.work_root, "executor-%d" % i)
            for name in ("executor.log", EXECUTOR_ID_FILE):
                try:
                    os.unlink(os.path.join(exec_dir, name))
                except OSError:
                    pass
            try:
                os.rmdir(exec_dir)
            except OSError:
                pass  # user files present (or already gone): keep
        try:
            os.rmdir(self.work_root)
        except OSError:
            if os.path.isdir(self.work_root):
                logger.info("keeping work root %s (user task files present)",
                            self.work_root)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def _drain_failing(q, reason):
    """Fail every task currently waiting in queue ``q`` with ``reason``."""
    while True:
        try:
            task = q.get_nowait()
        except queue.Empty:
            return
        if task is _STOP or not isinstance(task, dict):
            continue
        task["result"]._fail(task["task_id"], reason)


def _compose(transform, func):
    def full(raw_iter, _t=transform, _f=func):
        return _f(_t(raw_iter) if _t is not None else raw_iter)
    return full
