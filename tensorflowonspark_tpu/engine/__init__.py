"""Spark-shaped execution engine (the substrate the reference borrowed from Spark).

SURVEY.md §7 "Environment reality check": pyspark is not in the image, so
the framework supplies its own driver/executor engine with a Spark-
compatible *shape* — an RDD with partitions, closure-shipping tasks,
async partition jobs, and driver-visible task errors — sized to what the
cluster layer (cluster.py / node.py) actually needs. If real pyspark
appears later, a thin adapter can swap in underneath cluster.py, whose
surface deliberately mirrors ``TFCluster.run(sc, ...)``.

Pieces:
- :mod:`~tensorflowonspark_tpu.engine.rdd` — lazy partitioned collections.
- :mod:`~tensorflowonspark_tpu.engine.executor` — executor process main
  loop (connects back to the driver, runs tasks serially like a 1-core
  Spark executor).
- :mod:`~tensorflowonspark_tpu.engine.context` — driver context: spawns /
  accepts executors, schedules tasks, surfaces errors.
"""

from tensorflowonspark_tpu.engine.context import Context  # noqa: F401
from tensorflowonspark_tpu.engine.rdd import RDD  # noqa: F401
