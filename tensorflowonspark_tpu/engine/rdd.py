"""RDD: lazy partitioned collection with Spark-shaped operations.

Only the surface the reference framework actually exercises is implemented
(SURVEY.md §3 call stacks): ``parallelize`` data lives driver-side and
ships with tasks (exactly Spark's ``sc.parallelize`` semantics); transforms
compose lazily per partition; actions run jobs through the driver context.

``union`` matters more than it looks: the reference implements training
epochs as ``sc.union([dataRDD] * num_epochs)`` (SURVEY.md §3.2), so unioned
partitions must preserve order and re-run their transform chains
independently.
"""

import itertools


class _Partition(object):
    """One partition: a driver-side payload + a composed transform chain."""

    __slots__ = ("payload", "transform")

    def __init__(self, payload, transform=None):
        self.payload = payload
        self.transform = transform

    def compute(self):
        it = iter(self.payload)
        return self.transform(it) if self.transform is not None else it

    def with_transform(self, f):
        prev = self.transform

        def chained(it, _prev=prev, _f=f):
            return _f(_prev(it)) if _prev is not None else _f(it)

        return _Partition(self.payload, chained)


class RDD(object):
    def __init__(self, ctx, partitions):
        self.ctx = ctx
        self._partitions = list(partitions)

    # -- transformations (lazy) ------------------------------------------

    def mapPartitions(self, f):
        """f(iterator) -> iterator, applied per partition on the executor."""
        return RDD(self.ctx, [p.with_transform(f) for p in self._partitions])

    def mapPartitionsWithIndex(self, f):
        """f(index, iterator) -> iterator; index is the partition ordinal."""
        parts = []
        for i, p in enumerate(self._partitions):
            def indexed(it, _i=i, _f=f):
                return _f(_i, it)
            parts.append(p.with_transform(indexed))
        return RDD(self.ctx, parts)

    def map(self, f):
        return self.mapPartitions(lambda it, _f=f: (_f(x) for x in it))

    def flatMap(self, f):
        return self.mapPartitions(
            lambda it, _f=f: itertools.chain.from_iterable(_f(x) for x in it))

    def filter(self, f):
        return self.mapPartitions(lambda it, _f=f: (x for x in it if _f(x)))

    def union(self, other):
        return RDD(self.ctx, self._partitions + other._partitions)

    def coalesce(self, num_partitions):
        """Concatenate payloads into fewer partitions (driver-side data only;
        transforms must not have been applied yet — matches how the
        framework uses it, straight off ``parallelize``)."""
        if any(p.transform is not None for p in self._partitions):
            raise ValueError("coalesce() only supported before transformations")
        payload = [x for p in self._partitions for x in p.payload]
        return self.ctx.parallelize(payload, num_partitions)

    repartition = coalesce

    # -- actions ---------------------------------------------------------

    def getNumPartitions(self):
        return len(self._partitions)

    def collect(self):
        results = self.ctx.run_job(self, _collect_partition).get()
        return [x for part in results for x in part]

    def count(self):
        return sum(self.ctx.run_job(self, _count_partition).get())

    def take(self, n):
        """First n records, computing as few partitions as possible.

        Spark-shaped scan: try 1 partition, then geometrically larger
        batches (x4) until n records are gathered — a take(1) on a
        many-partition RDD costs one task, not a full job.
        """
        out = []
        i = 0
        width = 1
        while i < len(self._partitions) and len(out) < n:
            batch = self._partitions[i:i + width]
            # In-task limit: tasks return at most the records still
            # needed, never the whole partition (Spark's runJob shape).
            need = n - len(out)
            results = self.ctx.run_job(
                RDD(self.ctx, batch),
                lambda it, _k=need: list(itertools.islice(it, _k))).get()
            for part in results:
                out.extend(part)
                if len(out) >= n:
                    break
            i += len(batch)
            width *= 4
        return out[:n]

    def first(self):
        got = self.take(1)
        if not got:
            raise ValueError("RDD is empty")
        return got[0]

    def foreachPartition(self, f, exclude=()):
        """Run f over every partition; blocks; re-raises executor errors."""
        self.foreachPartitionAsync(f, exclude=exclude).get()

    def foreachPartitionAsync(self, f, one_task_per_executor=False,
                              fail_fast=True, exclude=()):
        """Async partition job -> :class:`AsyncResult` (reference:
        ``nodeRDD.foreachPartitionAsync(TFSparkNode.run(...))``).

        ``one_task_per_executor`` pins task i to executor i — the cluster
        bootstrap job needs exactly one node task resident per executor
        (SURVEY.md §3.1), a placement Spark gets from its scheduler and we
        make explicit. ``fail_fast=False`` opts out of
        abort-on-first-failure (cleanup jobs that must reach every
        executor). ``exclude`` bars the named executor ids from this job
        (the supervision plane's blacklist; see Context.run_job).
        """
        def run_and_discard(it, _f=f):
            _f(it)
            return None

        return self.ctx.run_job(self, run_and_discard,
                                one_task_per_executor=one_task_per_executor,
                                fail_fast=fail_fast, exclude=exclude)

    def saveAsTextFile(self, path):
        """Write one ``part-NNNNN`` file per partition under ``path``."""
        import os
        os.makedirs(path, exist_ok=False)
        results = self.ctx.run_job(self, _collect_partition).get()
        for i, part in enumerate(results):
            with open(os.path.join(path, "part-%05d" % i), "w") as fh:
                for x in part:
                    fh.write(str(x))
                    fh.write("\n")


def _collect_partition(it):
    return list(it)


def _count_partition(it):
    return sum(1 for _ in it)
