"""Host→device infeed: overlap transfer with the device step.

The TPU-native replacement for the reference's feed consumption idiom
(``tf.data.Dataset.from_generator(DataFeed...)`` — SURVEY.md §2.1 v2.x
examples). The reference moves records per-item through queues and hands
them to the TF runtime; here the host side assembles full device batches
and stages them into HBM *ahead* of the step so the device loop never
blocks on the host (SURVEY.md §7.3 "Feed throughput": async dispatch gives
the overlap almost free — keep the device loop un-blocked).

Two layers:

- :func:`prefetch` — wrap any batch iterator with an N-deep background
  staging pipeline (``jax.device_put`` on a worker thread; JAX transfers
  are async, so the thread mostly just *initiates* DMA early).
- :func:`sharded_batches` — also lay each batch out with a
  ``NamedSharding`` over a mesh (batch dim split over the data axis), so
  the arrays arrive ready for a pjit-ed step function.
"""

import queue as _queue
import threading

_END = object()


def prefetch(batch_iter, size=2, device_put=None):
    """Iterate ``batch_iter`` with ``size`` batches staged ahead.

    ``device_put``: callable applied to each batch on the staging thread
    (default ``jax.device_put`` — leaves layout to JAX). The generator
    yields staged batches in order. Exceptions on the staging thread
    re-raise at the consuming ``next()``.
    """
    import jax

    put = device_put or jax.device_put
    buf = _queue.Queue(maxsize=size)

    def _stage():
        try:
            for batch in batch_iter:
                buf.put(jax.tree.map(put, batch))
            buf.put(_END)
        except BaseException as e:  # noqa: BLE001 - re-raised at next()
            buf.put(e)

    t = threading.Thread(target=_stage, name="infeed-prefetch", daemon=True)
    t.start()

    while True:
        item = buf.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def sharded_batches(batch_iter, mesh, axis="data", size=2):
    """Prefetch + shard: yield batches laid out over ``mesh``'s data axis.

    Each array's leading dim is split across ``axis`` (must divide it);
    everything arrives as committed global arrays, so a pjit-ed step with
    matching in_shardings runs without any implicit resharding.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(axis))

    def put(x):
        return jax.device_put(x, sharding)

    return prefetch(batch_iter, size=size, device_put=put)
