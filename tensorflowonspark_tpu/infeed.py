"""Host→device infeed: overlap transfer with the device step.

The TPU-native replacement for the reference's feed consumption idiom
(``tf.data.Dataset.from_generator(DataFeed...)`` — SURVEY.md §2.1 v2.x
examples). The reference moves records per-item through queues and hands
them to the TF runtime; here the host side assembles full device batches
and stages them into HBM *ahead* of the step so the device loop never
blocks on the host (SURVEY.md §7.3 "Feed throughput": async dispatch gives
the overlap almost free — keep the device loop un-blocked).

Two layers:

- :func:`prefetch` — wrap any batch iterator with an N-deep background
  staging pipeline (``jax.device_put`` on a worker thread; JAX transfers
  are async, so the thread mostly just *initiates* DMA early).
- :func:`sharded_batches` — also lay each batch out with a
  ``NamedSharding`` over a mesh (batch dim split over the data axis), so
  the arrays arrive ready for a pjit-ed step function.
"""

import queue as _queue
import threading
import time

_END = object()


def prefetch(batch_iter, size=2, device_put=None, timers=None):
    """Iterate ``batch_iter`` with ``size`` batches staged ahead.

    ``device_put``: callable applied to each batch on the staging thread
    (default ``jax.device_put`` — leaves layout to JAX). The generator
    yields staged batches in order. Exceptions on the staging thread
    re-raise at the consuming ``next()``.

    ``timers``: optional :class:`tracing.StageTimers`; each batch's
    host→device transfer dispatch lands in its ``device_put`` stage.
    Pass the consuming DataFeed's ``.timers`` so the whole feed-plane
    breakdown (ring wait / decode / gather / device_put) shares one
    snapshot — ``feed.stats()["stages"]`` then attributes every host-
    side millisecond of the fed path.

    Staging-buffer caveat: DataFeed's mapped columnar batches are
    REUSED buffers (valid until its next ``next_batch``). The default
    ``jax.device_put`` can ZERO-COPY alias an aligned numpy array on
    the CPU backend, so feeding DataFeed batches through this plain
    prefetch on CPU can alias staged arrays to memory the feed will
    overwrite. Use :func:`sharded_batches` (its per-shard puts copy —
    the canonical consumption everywhere in this framework), pass a
    copying ``device_put``, or set ``TFOS_FEED_STAGING=0`` on the feed.

    Closing the generator early (break, ``inference terminate()``, an
    error in the consumer) cancels and joins the staging thread — a bare
    ``buf.put`` there would strand the thread forever on a full queue,
    holding staged device arrays, once per abandoned feed.
    """
    import jax

    put = device_put or jax.device_put
    buf = _queue.Queue(maxsize=size)
    stop = threading.Event()

    def _put(item):
        """Bounded put that observes cancellation; False when cancelled."""
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _stage():
        try:
            for batch in batch_iter:
                t0 = time.monotonic()
                staged = jax.tree.map(put, batch)
                if timers is not None:
                    timers.add("device_put", time.monotonic() - t0)
                if stop.is_set() or not _put(staged):
                    return
            _put(_END)
        except BaseException as e:  # noqa: BLE001 - re-raised at next()
            _put(e)

    t = threading.Thread(target=_stage, name="infeed-prefetch", daemon=True)
    t.start()

    try:
        while True:
            item = buf.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        try:  # unblock a put-in-flight so the join below is prompt
            while True:
                buf.get_nowait()
        except _queue.Empty:
            pass
        t.join(timeout=5.0)


def sharded_batches(batch_iter, mesh, axis="data", size=2, timers=None):
    """Prefetch + shard: yield batches laid out over ``mesh``'s data axis.

    Each array's leading dim is split across ``axis`` (must divide it);
    everything arrives as committed global arrays, so a pjit-ed step with
    matching in_shardings runs without any implicit resharding. A SPLIT
    axis's per-shard ``device_put`` copies out of the host batch (each
    shard is a slice), so DataFeed's reusable staging buffers are safe
    to hand straight in here; a 1-device axis's "shard" is the whole
    array, which ``jax.device_put`` can ZERO-COPY alias on the CPU
    backend (measured) — there the copy is forced explicitly, or
    prefetched-but-unconsumed batches would be silently overwritten by
    the feed's next gather. ``timers`` forwards to :func:`prefetch`.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(axis))
    n_shards = int(mesh.shape[axis])

    def put(x):
        if n_shards == 1 and isinstance(x, np.ndarray):
            x = np.array(x, copy=True)
        return jax.device_put(x, sharding)

    return prefetch(batch_iter, size=size, device_put=put, timers=timers)
