"""Tracing / profiling / metrics hookup.

Reference posture (SURVEY.md §5 "Tracing/profiling"): the reference only
wires TensorBoard (subprocess on one node) and leaves summaries to user
code; its own plumbing is unobservable. Here the framework exposes:

- :func:`start_profiler_server` — per-host ``jax.profiler`` server, so
  TensorBoard's profile plugin (or ``xprof``) can capture device traces.
- :func:`trace` — context manager around ``jax.profiler.trace`` for
  programmatic capture windows.
- :class:`SummaryWriter` — scalar/text summaries for TensorBoard, backed
  by the installed TF's ``tf.summary`` (CPU TF is in the image); no-ops
  cleanly when TF is absent.
- :func:`metrics_hook` — a ``Trainer.train_loop`` hook writing loss +
  step rate, the part the reference couldn't see (queue-fed step timing).
- :class:`StageTimers` — named wall-clock accumulators for the feed
  plane's per-stage breakdown (ring wait / decode / gather /
  device_put): DataFeed and infeed.prefetch share one instance so the
  whole host-side feed cost of a run lands in a single snapshot, and
  bench.py / scripts/profile_fed.py surface it next to
  ``fed_frac_of_device`` — the remaining feed loss is attributed to a
  stage instead of unexplained.
- :class:`Counters` — named monotonic counters + gauges for scheduler
  loops: serving.DecodeEngine exports queue depth, slot occupancy,
  tokens-per-step, and the request-lifecycle tallies (``shed`` /
  ``cancelled`` / ``deadline_exceeded`` / ``engine_restarts``) through
  one of these; bench.py / scripts/profile_serving.py read the
  snapshots and ModelServer's /healthz serves them live.
- :class:`EventLog` — timestamped named events for the supervision plane
  (supervisor.py): failure detected, attempt torn down, cluster
  reformed, checkpoint restored, first post-restore step. The MTTR
  numbers ``bench.py recovery`` and scripts/profile_recovery.py publish
  are spans over one of these logs. Bounded: a ring of ``capacity``
  events (default 4096) plus a ``dropped`` counter, so a long
  supervised run cannot grow it without limit.

The unified observability plane (PR 5) lives here too:

- :class:`Histogram` — fixed log-bucket latency distribution with
  ``quantile(q)``: the serving engine records TTFT / per-token /
  decode-step / queue-wait / request / drain times into these, and
  bench.py + the profile scripts read p50/p95/p99 from them instead of
  keeping private sample lists.
- :class:`MetricsRegistry` — one named home for Counters, StageTimers,
  and Histograms, with :meth:`MetricsRegistry.render` producing
  OpenMetrics text (``GET /metrics`` on ModelServer and the
  reservation server's driver-side stats endpoint) and
  :meth:`MetricsRegistry.snapshot` producing the compact JSON-able
  form that piggybacks on BEAT heartbeat leases for cluster-wide
  aggregation (:func:`merge_snapshots`, ``cluster.metrics()``).
- :data:`METRIC_FAMILIES` — the canonical catalog of every exported
  metric family. scripts/metrics_lint.py asserts this table and
  docs/observability.md's catalog agree, and
  tests/test_observability.py asserts a live scrape renders only
  cataloged families — name drift is caught at both ends.
- :class:`FlightRecorder` — bounded ring of request-scoped span events
  (admit -> queue -> prefill -> decode -> finish/evict/shed, one trace
  id per serving request), dumpable as Chrome trace-event JSON that
  loads in Perfetto (``GET /debug/trace``, scripts/trace_dump.py). The
  process-global recorder (:func:`flight_recorder`) doubles as the
  black box the Supervisor dumps into incident evidence.
"""

import collections
import itertools
import logging
import math
import os
import threading
import time

logger = logging.getLogger(__name__)


class StageTimers(object):
    """Named wall-clock accumulators: one entry per pipeline stage.

    Cheap enough for per-chunk use (a dict add per sample, no locks).
    The feed plane's convention is one instance per DataFeed, shared
    with the infeed prefetcher (``infeed.prefetch(..., timers=...)``);
    the prefetch staging thread is the only cross-thread writer and
    ``snapshot()`` is read at end of run, so the unlocked add is a
    benign last-sample race, never a torn total.
    """

    __slots__ = ("_t", "_n")

    def __init__(self):
        self._t = {}
        self._n = {}

    def add(self, stage, seconds):
        """Accumulate one sample for ``stage``."""
        self._t[stage] = self._t.get(stage, 0.0) + seconds
        self._n[stage] = self._n.get(stage, 0) + 1

    def timed(self, stage):
        """``with timers.timed("decode"):`` — context-manager sampling."""
        return _StageSpan(self, stage)

    def snapshot(self):
        """{stage: total_seconds} — stable copy for artifacts/logs."""
        return dict(self._t)

    def counts(self):
        """{stage: samples} — for per-sample (per-chunk/batch) math."""
        return dict(self._n)

    def per_ms(self):
        """{stage: mean milliseconds per sample} — the human-readable
        breakdown bench.py and profile_fed.py print."""
        return {k: round(v * 1000.0 / max(self._n.get(k, 1), 1), 3)
                for k, v in self._t.items()}


class Counters(object):
    """Named monotonic counters + gauges for a serving/scheduler loop.

    The feed plane's :class:`StageTimers` answers "where did the time
    go"; this answers "what did the loop do" — requests queued, slots
    occupied, tokens emitted per step. Single-writer convention (the
    owning scheduler thread); readers take :meth:`snapshot` copies, so
    the unlocked dict ops are benign under the GIL exactly like
    StageTimers' adds.
    """

    __slots__ = ("_counts", "_gauges")

    def __init__(self):
        self._counts = {}
        self._gauges = {}

    def inc(self, name, n=1):
        """Add ``n`` to monotonic counter ``name``."""
        self._counts[name] = self._counts.get(name, 0) + n

    def gauge(self, name, value):
        """Set instantaneous gauge ``name`` (e.g. queue depth)."""
        self._gauges[name] = value

    def get(self, name):
        """Current value of counter ``name`` (0 when absent) — so the
        owning loop can branch on its own tallies without keeping a
        parallel ledger."""
        return self._counts.get(name, 0)

    def set_count(self, name, value):
        """Set counter ``name`` absolutely — for MIRRORING an external
        monotonic source (e.g. a FlightRecorder's ``dropped`` tally)
        into the exposition; never for resetting. The mirror stays
        monotonic as long as the source is."""
        self._counts[name] = value

    def snapshot(self):
        """{"counts": {...}, "gauges": {...}} — stable copies."""
        return {"counts": dict(self._counts), "gauges": dict(self._gauges)}

    def rate(self, numerator, denominator):
        """counts[numerator] / counts[denominator] (0 when empty) — e.g.
        ``rate("decode_tokens", "decode_steps")`` = mean decode
        occupancy per step."""
        d = self._counts.get(denominator, 0)
        return self._counts.get(numerator, 0) / d if d else 0.0


class EventLog(object):
    """Bounded timestamped event record for supervision timelines.

    Each event carries both clocks: ``t`` (monotonic — span math) and
    ``wall`` (epoch — correlating with out-of-process evidence like a
    chaos fuse file's fire time). Thread-safe: the supervisor's monitor
    thread and the supervised-run driver loop both append.

    ``capacity`` bounds the log to a ring of the most recent events
    (default 4096 — a supervised run that beats forever must not grow
    driver memory without limit); overflow evicts the OLDEST event and
    increments :attr:`dropped`. Span extraction (``span``,
    ``supervisor.recovery_stages``) therefore describes the retained
    window — at the default capacity that is far more history than any
    MTTR computation needs.
    """

    def __init__(self, capacity=4096):
        self._events = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        #: events evicted by the ring bound (monotonic counter)
        self.dropped = 0

    def record(self, name, **detail):
        """Append one event; returns its dict (already stamped)."""
        event = {"name": name, "t": time.monotonic(), "wall": time.time()}
        if detail:
            event.update(detail)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(event)
        # mirror into the process-global flight recorder: supervision
        # milestones land in the same black box serving spans do, so an
        # incident dump reads as one interleaved timeline
        flight_recorder().instant(name, **detail)
        logger.debug("event %s %s", name, detail)
        return event

    def events(self, name=None):
        """All events (or those named ``name``), oldest first."""
        with self._lock:
            events = list(self._events)
        if name is not None:
            events = [e for e in events if e["name"] == name]
        return events

    def last(self, name, **match):
        """Most recent event named ``name`` whose fields match, or None."""
        for event in reversed(self.events(name)):
            if all(event.get(k) == v for k, v in match.items()):
                return event
        return None

    def span(self, from_name, to_name, **match):
        """Seconds between the last matching ``from_name`` and the first
        matching ``to_name`` at or after it; None when either is absent.
        The from/to pairing is how MTTR stages (detect -> reform ->
        restore -> first step) are extracted from one log."""
        start = self.last(from_name, **match)
        if start is None:
            return None
        for event in self.events(to_name):
            if event["t"] >= start["t"] and \
                    all(event.get(k) == v for k, v in match.items()):
                return event["t"] - start["t"]
        return None


#: content type every /metrics response declares (OpenMetrics
#: exposition) — shared by ModelServer and the reservation server's
#: driver-side stats endpoint so scrapers see ONE contract
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: canonical catalog of every exported OpenMetrics family:
#: {family: (type, labels, meaning)}. The family name is what appears in
#: the ``# TYPE`` line; counters expose ``<family>_total`` samples and
#: histograms expose ``_bucket``/``_sum``/``_count``. scripts/
#: metrics_lint.py asserts this table and docs/observability.md's
#: catalog agree (``make metrics-lint``), and tests assert a live
#: ``/metrics`` scrape renders ONLY cataloged families — so a metric
#: added in code without a catalog row (or vice versa) fails loudly.
METRIC_FAMILIES = {
    # -- serving plane (DecodeEngine registry; ModelServer /metrics) --
    "tfos_serving_ttft_seconds":
        ("histogram", "", "submit -> first emitted token"),
    "tfos_serving_token_latency_seconds":
        ("histogram", "", "gap between consecutive emitted tokens"),
    "tfos_serving_decode_step_seconds":
        ("histogram", "", "one fixed-shape decode step, wall clock"),
    "tfos_serving_queue_wait_seconds":
        ("histogram", "", "submit -> prefill start (admission queue)"),
    "tfos_serving_request_seconds":
        ("histogram", "", "submit -> completion, whole request"),
    "tfos_serving_drain_seconds":
        ("histogram", "", "DecodeEngine.drain wall clock"),
    "tfos_serving_tokens":
        ("counter", "", "tokens emitted (prefill firsts included)"),
    "tfos_serving_decode_tokens":
        ("counter", "", "tokens emitted by decode steps only"),
    "tfos_serving_decode_steps":
        ("counter", "", "fixed-shape decode steps run"),
    "tfos_serving_prefills":
        ("counter", "", "prompt prefills (one per admission)"),
    "tfos_serving_requests_completed":
        ("counter", "", "requests finished normally (EOS/length)"),
    "tfos_serving_shed":
        ("counter", "", "requests refused at admission (infeasible "
                        "deadline)"),
    "tfos_serving_cancelled":
        ("counter", "", "requests evicted by cancel/disconnect"),
    "tfos_serving_deadline_exceeded":
        ("counter", "", "requests evicted past their deadline"),
    "tfos_serving_engine_restarts":
        ("counter", "", "RestartEngine rebuilds of a dead scheduler"),
    # -- paged KV cache (PR 8): block pool + prefix cache --
    "tfos_serving_kv_blocks_total":
        ("gauge", "", "usable KV blocks in the paged pool (0 on a "
                      "contiguous engine)"),
    "tfos_serving_kv_blocks_free":
        ("gauge", "", "KV blocks obtainable right now (free list + "
                      "evictable prefix-cached)"),
    "tfos_serving_kv_blocks_cached":
        ("gauge", "", "refcount-0 blocks retained by the prefix cache "
                      "(evictable subset of kv_blocks_free)"),
    "tfos_serving_prefix_hit_blocks":
        ("counter", "", "shareable prompt blocks found resident at "
                        "admission (each skips its share of prefill)"),
    "tfos_serving_prefix_miss_blocks":
        ("counter", "", "shareable prompt blocks NOT resident at "
                        "admission (prefilled fresh)"),
    "tfos_serving_prefix_evictions":
        ("counter", "", "prefix-cached blocks reclaimed by the LRU "
                        "under allocation pressure"),
    "tfos_serving_preemptions":
        ("counter", "", "in-flight requests preempted (blocks freed, "
                        "requeued for continuation) under pool "
                        "exhaustion"),
    # -- fused paged attention + generated-prefix registration (PR 11) --
    "tfos_serving_attn_impl":
        ("gauge", "impl", "constant 1 carrying the engine's attention "
                          "formulation (fused / gather / contiguous) — "
                          "info-pattern join key for kernel-config "
                          "rollouts across a fleet"),
    "tfos_serving_generated_prefix_registered":
        ("counter", "", "decode-GENERATED full blocks published into "
                        "the prefix registry (multi-turn conversation "
                        "reuse; prompt-block registrations excluded)"),
    "tfos_serving_generated_prefix_hit_blocks":
        ("counter", "", "prefix-cache block hits that landed on a "
                        "decode-generated registration (subset of "
                        "tfos_serving_prefix_hit_blocks; preemption "
                        "continuations re-hitting their own blocks "
                        "excluded)"),
    # -- prefix-chain digest export (PR 16): BEAT-carried warmth --
    "tfos_serving_prefix_digest_chains":
        ("gauge", "", "resident prefix chains the engine's bounded "
                      "top-K digest currently publishes in its BEAT "
                      "payload (0 on a contiguous engine)"),
    "tfos_serving_prefix_digest_truncated":
        ("gauge", "", "1 when the registry holds more chains than the "
                      "digest's top-K bound (the published digest is "
                      "an honest subset), else 0"),
    # -- speculative decoding + int8 paged KV (PR 15) --
    "tfos_serving_spec_proposed":
        ("counter", "", "draft tokens proposed by speculative rounds, "
                        "clamped to each request's emittable window "
                        "min(speculate_k, remaining) — so between 1x "
                        "and speculate_k x tfos_serving_spec_rounds"),
    "tfos_serving_spec_accepted":
        ("counter", "", "proposed draft tokens the target's verify "
                        "accepted (<= proposed; accepted/proposed is "
                        "the live acceptance rate load_stats and the "
                        "BEAT payload carry)"),
    "tfos_serving_spec_rounds":
        ("counter", "", "speculative draft+verify rounds run, counted "
                        "per active slot (a round over 3 slots counts "
                        "3)"),
    "tfos_serving_kv_dtype":
        ("gauge", "dtype", "constant 1 carrying the engine's KV pool "
                           "storage dtype (int8 fast path vs the "
                           "compute dtype) — info-pattern join key "
                           "for quantization rollouts across a "
                           "fleet"),
    "tfos_serving_queue_depth":
        ("gauge", "", "requests waiting for a slot"),
    "tfos_serving_slot_occupancy":
        ("gauge", "", "slots holding an in-flight sequence"),
    "tfos_serving_stage_seconds":
        ("counter", "stage", "scheduler wall seconds per stage "
                             "(qos_plan / prefill / decode_step / "
                             "host_schedule; speculative engines add "
                             "spec_round / draft_prefill plus the "
                             "draft and verify probes, int8 engines "
                             "the dequant probe)"),
    "tfos_serving_stage_samples":
        ("counter", "stage", "samples behind tfos_serving_stage_seconds"),
    "tfos_serving_replica_info":
        ("gauge", "replica_id", "constant 1 carrying the engine's stable "
                                "replica identity (join key for scraped "
                                "series and router decisions)"),
    # -- idempotent dispatch (PR 12): replica-side dedup window --
    "tfos_serving_dedup_hits":
        ("counter", "", "retried/duplicated requests answered from the "
                        "dedup window's stored completion (executed "
                        "once, replayed — the partition-flap proof "
                        "that retries were absorbed)"),
    "tfos_serving_dedup_joined":
        ("counter", "", "duplicate deliveries that JOINED a still-"
                        "executing original instead of racing a second "
                        "generation"),
    # -- multi-tenant QoS plane (PR 18) --
    "tfos_qos_admitted":
        ("counter", "tenant,class", "admissions the weighted-fair "
                                    "scheduler granted, by tenant and "
                                    "priority class"),
    "tfos_qos_preemptions":
        ("counter", "tenant,class", "in-flight sequences preempted, by "
                                    "the tenant/class that was evicted "
                                    "(pool exhaustion or a stronger "
                                    "class waiting; subset context for "
                                    "tfos_serving_preemptions)"),
    "tfos_qos_quota_rejections":
        ("counter", "tenant", "admissions refused 429 QuotaExceeded "
                              "because the tenant's token bucket was "
                              "in debt"),
    "tfos_qos_tokens":
        ("counter", "tenant", "tokens actually delivered per tenant "
                              "(the post-paid usage that drains its "
                              "quota bucket)"),
    "tfos_qos_queue_wait_high_seconds":
        ("histogram", "", "submit -> prefill start for HIGH-class "
                          "admissions (per-class split of "
                          "tfos_serving_queue_wait_seconds — the "
                          "isolation number the antagonist bench "
                          "pins)"),
    "tfos_qos_queue_wait_normal_seconds":
        ("histogram", "", "submit -> prefill start for normal-class "
                          "admissions"),
    "tfos_qos_queue_wait_low_seconds":
        ("histogram", "", "submit -> prefill start for LOW-class "
                          "admissions (grows under pressure by "
                          "design: LOW absorbs the backlog)"),
    # -- fleet plane (FleetRouter registry; router /metrics) --
    "tfos_fleet_requests":
        ("counter", "", "requests the router answered (any status)"),
    "tfos_fleet_failovers":
        ("counter", "", "upstream attempts abandoned for another replica "
                        "after a retriable failure"),
    "tfos_fleet_no_replica":
        ("counter", "", "dispatch attempts that found no routable replica"),
    "tfos_fleet_probes":
        ("counter", "", "half-open health probes sent to down replicas"),
    "tfos_fleet_client_disconnects":
        ("counter", "", "dispatches abandoned because the router's own "
                        "client disconnected (upstream torn down so "
                        "the replica's disconnect cancel fires)"),
    "tfos_fleet_hedges":
        ("counter", "", "hedge attempts launched (primary still "
                        "running past the quantile-derived hedge "
                        "delay)"),
    "tfos_fleet_hedge_wins":
        ("counter", "", "requests whose HEDGE attempt produced the "
                        "winning response (the gray-replica tail the "
                        "hedge clipped)"),
    "tfos_fleet_fenced_upstreams":
        ("counter", "", "upstream attempts answered 410 Fenced (stale "
                        "lease epoch) — failed over and hard-downed"),
    "tfos_fleet_replicas":
        ("gauge", "", "replicas with a live serving lease"),
    "tfos_fleet_replicas_routable":
        ("gauge", "", "replicas currently eligible for dispatch"),
    "tfos_fleet_request_seconds":
        ("histogram", "", "router-observed request wall clock "
                          "(all dispatch attempts included)"),
    "tfos_fleet_upstream_seconds":
        ("histogram", "", "one upstream POST attempt, wall clock"),
    "tfos_fleet_route_overhead_seconds":
        ("histogram", "", "request wall clock minus its upstream "
                          "attempts (pick + failover bookkeeping)"),
    "tfos_fleet_stage_seconds":
        ("counter", "stage", "router wall seconds per stage "
                             "(pick / upstream / prefill)"),
    "tfos_fleet_stage_samples":
        ("counter", "stage", "samples behind tfos_fleet_stage_seconds"),
    "tfos_fleet_replica_up":
        ("gauge", "replica", "1 when the replica is routable, 0 when "
                             "down / stale / draining / quiesced"),
    "tfos_fleet_replica_lease_age_seconds":
        ("gauge", "replica", "seconds since each replica's last BEAT"),
    "tfos_fleet_replica_inflight":
        ("gauge", "replica", "requests the router holds open against "
                             "each replica"),
    # -- prefix-aware routing + session affinity (PR 16) --
    "tfos_fleet_affinity_hits":
        ("counter", "", "dispatches whose first-pick replica was WARM "
                        "for the request (session-affinity hint or "
                        "beat-digest prefix match promoted it over "
                        "pure least-loaded order)"),
    "tfos_fleet_affinity_breaks":
        ("counter", "reason", "times affinity was deliberately NOT "
                              "honored: load_guard (warm replica past "
                              "the backlog guard lost to a colder "
                              "one), failover_cold (warm replica dead/"
                              "fenced/draining — served cold, map "
                              "entry evicted), hedge_cold_win (a cold "
                              "hedge beat the warm primary; map left "
                              "unpoisoned)"),
    "tfos_fleet_affinity_entries":
        ("gauge", "", "live session -> replica entries in the "
                      "router's TTL'd affinity map"),
    # -- prefill/decode disaggregation: two-stage dispatch (PR 17) --
    "tfos_fleet_prefill_dispatches":
        ("counter", "", "staged :prefill calls the two-stage "
                        "dispatcher sent to the prefill tier"),
    "tfos_fleet_prefill_ships":
        ("counter", "", "staged prefills whose KV blocks were "
                        "confirmed shipped to the chosen decode "
                        "replica (the decode attempt then lands "
                        "warm)"),
    "tfos_fleet_prefill_skips":
        ("counter", "", "stages skipped because the chosen decode "
                        "replica already held the prompt's prefix "
                        "(digest match — nothing to ship)"),
    "tfos_fleet_prefill_misses":
        ("counter", "", "staged prefills that completed WITHOUT a "
                        "confirmed ship (splice refused, transport "
                        "failed, or unshippable) — the decode side "
                        "re-prefills cold"),
    "tfos_fleet_prefill_errors":
        ("counter", "", "prefill stages abandoned on a transport/"
                        "routing error (partitioned or dead prefill "
                        "tier; the request degrades to single-stage "
                        "dispatch)"),
    "tfos_fleet_replica_tier":
        ("gauge", "replica,tier", "constant 1 joining each replica to "
                                  "its serving tier (prefill / decode "
                                  "/ mixed) — the disaggregation "
                                  "topology at a glance"),
    # -- multi-tenant QoS at the router (PR 18) --
    "tfos_fleet_quota_rejections":
        ("counter", "", "dispatches the ROUTER refused 429 "
                        "QuotaExceeded from its own quota table "
                        "before any upstream attempt (engine-side "
                        "refusals count in tfos_qos_quota_rejections "
                        "on the replica)"),
    "tfos_fleet_tenant_spreads":
        ("counter", "", "dispatches re-ordered away from a replica "
                        "already concentrating the requesting "
                        "tenant's backlog (burst spreading; affinity "
                        "preferences still win)"),
    "tfos_fleet_prefix_prewarms":
        ("counter", "", "predictive placements triggered: a tenant's "
                        "hot prefix saturated its warm replica past "
                        "the load guard, so the router staged the "
                        "prefix onto the chosen cold replica via the "
                        "kv-ship plane (PR 16's digest follow-up)"),
    # -- executor-hosted serving + SLO autoscaler (PR 13) --
    "tfos_serving_replica_host":
        ("gauge", "replica_id,executor", "constant 1 joining each "
                                         "executor-hosted replica to "
                                         "the executor that runs it "
                                         "(absent for driver-local "
                                         "replicas)"),
    "tfos_autoscale_decisions":
        ("counter", "", "autoscale control-loop evaluations (every "
                        "poll, holds included)"),
    "tfos_autoscale_scale_ups":
        ("counter", "", "replicas added by the autoscaler (SLO breach "
                        "-> spawn on a free executor)"),
    "tfos_autoscale_scale_downs":
        ("counter", "", "replicas retired by the autoscaler (sustained "
                        "idle -> zero-loss drain retirement)"),
    "tfos_autoscale_replacements":
        ("counter", "", "dead replicas repaired under the same "
                        "identity (lease expiry -> fenced replacement "
                        "spawn, or in-place respawn RPC)"),
    "tfos_autoscale_scale_up_blocked":
        ("counter", "", "scale-ups (or replacements) the capacity gate "
                        "refused — no free executor existed"),
    "tfos_autoscale_unclean_retirements":
        ("counter", "", "scale-down drains that timed out or failed "
                        "(zero-loss retirement is the contract; this "
                        "counting up is an alert)"),
    "tfos_autoscale_replicas_live":
        ("gauge", "", "replicas with a fresh lease and a live engine, "
                      "as the autoscaler last counted them"),
    "tfos_autoscale_replicas_target":
        ("gauge", "", "replica count the autoscaler currently wants "
                      "(live adjusted by its latest decision)"),
    # -- feed plane (DataFeed registry; BEAT-piggybacked to the driver) --
    "tfos_feed_stage_seconds":
        ("counter", "stage", "host-side feed wall seconds per stage "
                             "(ring_wait / queue_wait / decode / gather "
                             "/ device_put)"),
    "tfos_feed_stage_samples":
        ("counter", "stage", "samples behind tfos_feed_stage_seconds"),
    "tfos_feed_records":
        ("counter", "", "records consumed off the feed transport"),
    "tfos_feed_chunks":
        ("counter", "", "chunks consumed off the feed transport"),
    "tfos_feed_batches":
        ("counter", "", "non-empty batches served to the trainer"),
    "tfos_feed_staging_alloc":
        ("counter", "", "staging-buffer allocations (gather path)"),
    "tfos_feed_staging_reuse":
        ("counter", "", "staging-buffer reuses (gather path)"),
    # -- cluster rollup (reservation server's driver-side /metrics) --
    "tfos_cluster_executors":
        ("gauge", "", "executors with a live heartbeat lease"),
    "tfos_cluster_train_step":
        ("gauge", "executor", "last training step each executor beat"),
    "tfos_cluster_feed_hb_batches":
        ("gauge", "executor", "DataFeed batches-served progress counter"),
    "tfos_cluster_lease_age_seconds":
        ("gauge", "executor", "seconds since each executor's last beat"),
    "tfos_cluster_width":
        ("gauge", "", "executors in the live formation (elastic resize "
                      "shrinks/grows this)"),
    "tfos_cluster_width_target":
        ("gauge", "", "the job's configured width (width < target means "
                      "running degraded after a shrink)"),
    # -- goodput plane (goodput.py; rides the feed registry's BEAT
    # snapshot; rendered per-executor on the driver /metrics) --
    "tfos_badput_seconds":
        ("counter", "stage", "non-productive wall seconds per badput "
                             "category (compile / checkpoint_save / "
                             "restore / reform / resize_drain / "
                             "feed_wait / idle)"),
    "tfos_badput_samples":
        ("counter", "stage", "samples behind tfos_badput_seconds"),
    "tfos_goodput_productive_seconds":
        ("counter", "", "wall seconds spent in productive training "
                        "steps (the goodput numerator)"),
    "tfos_goodput_steps":
        ("counter", "", "productive training steps accounted by the "
                        "goodput ledger"),
    "tfos_goodput_ratio":
        ("gauge", "", "productive_seconds / ledger wall time (per "
                      "process; derive cluster ratios from the summed "
                      "seconds, not by summing this gauge)"),
    "tfos_goodput_step_ewma_seconds":
        ("gauge", "", "EWMA of recent productive step wall times (the "
                      "straggler detector's per-executor signal)"),
    "tfos_goodput_wall_seconds":
        ("gauge", "", "the ledger's measured wall time, published "
                      "atomically with its categories — verify "
                      "sum(categories) == this against one snapshot"),
    "tfos_train_step_skew":
        ("gauge", "executor", "executor step-time EWMA / fleet "
                              "lower-median (driver-computed; the "
                              "SLOW straggler signature — a STALLED "
                              "executor's EWMA freezes, so stalls "
                              "surface via the straggler incident, "
                              "not this gauge)"),
    # -- trace plane (FlightRecorder ring saturation) --
    "tfos_trace_spans_dropped":
        ("counter", "", "span events evicted from the FlightRecorder "
                        "ring (capacity overflow — raise capacity or "
                        "dump more often if this grows)"),
    # -- KV shipping plane (PR 17 prefill/decode disaggregation) --
    "tfos_kv_ship_bytes":
        ("counter", "", "PHYSICAL bytes of KV shipments successfully "
                        "delivered from this replica (codes + scales "
                        "as transferred — an int8 pool ships ~3.2x "
                        "fewer bytes than the dequantized size; never "
                        "priced logically)"),
    "tfos_kv_ship_blocks":
        ("counter", "", "KV blocks successfully shipped from this "
                        "replica to a decode-tier peer"),
    "tfos_kv_spliced_bytes":
        ("counter", "", "physical bytes of NOVEL shipped rows spliced "
                        "into this replica's pool (dedupe-skipped "
                        "blocks contribute nothing)"),
    "tfos_kv_spliced_blocks":
        ("counter", "", "shipped blocks adopted into this replica's "
                        "pool by block-table splice"),
    "tfos_kv_ship_ms":
        ("histogram", "", "wall milliseconds per successful shipment "
                          "(pack + transport + splice verdict, as the "
                          "shipping side observes it)"),
    "tfos_splice_failures":
        ("counter", "reason", "shipments this replica refused or "
                              "failed to splice, by bounded reason "
                              "(fenced / block_size / kv_dtype / "
                              "pool_exhausted / malformed / unpaged / "
                              "engine) — 'fenced' growing means a "
                              "retired incarnation is still shipping"),
    # -- control-plane survivability (PR 19) --
    "tfos_serving_beat_reconnects":
        ("counter", "", "beat-loop reconnects to the reservation "
                        "server (bounded jittered retry after a "
                        "connection-level beat failure; the lease "
                        "re-registers with its SAME epoch)"),
    "tfos_control_epoch":
        ("gauge", "", "current control epoch (router leadership "
                      "fence) as the reservation server publishes it; "
                      "absent until one is minted"),
    "tfos_control_recovery_pending":
        ("gauge", "", "journal-seeded identities a restarted "
                      "reservation server is still waiting to hear "
                      "re-announce (0 once recovery completes or the "
                      "grace window expires)"),
    "tfos_control_takeovers":
        ("counter", "", "warm-standby router takeovers (leader death "
                        "detected -> higher control epoch minted -> "
                        "standby serving)"),
    "tfos_control_admin_rejections":
        ("counter", "", "admin RPCs a replica refused 409 "
                        "ControlFenced because the caller stamped a "
                        "control epoch below the replica's floor (a "
                        "deposed driver is still issuing writes)"),
    # -- serving SLO plane (slo.py) ------------------------------------
    "tfos_fleet_affinity_resets":
        ("counter", "reason", "times a router came up with an EMPTY "
                              "AffinityMap over a fleet that already "
                              "held serving sessions (takeover = warm-"
                              "standby promotion, restart = same-name "
                              "router restart): the honest explanation "
                              "for a warm-hit-rate dip after failover"),
    "tfos_slo_error_budget_remaining":
        ("gauge", "slo,tenant", "fraction of the error budget left "
                                "over the slowest window (1 - burn); "
                                "negative when the budget is spent"),
    "tfos_slo_burn_rate":
        ("gauge", "slo,tenant,window", "error-budget burn multiple per "
                                       "window (1.0 = spending exactly "
                                       "the budget)"),
    "tfos_slo_alerts":
        ("counter", "slo", "burn-rate alert raises per SLO (clears do "
                           "not decrement; the count is incident "
                           "history)"),
    "tfos_slo_canary_probes":
        ("counter", "", "synthetic canary probes issued through the "
                        "real router path under the reserved "
                        "low-priority canary tenant"),
    "tfos_slo_canary_failures":
        ("counter", "", "canary probes that failed (non-200 or "
                        "transport error): black-box availability"),
    "tfos_slo_canary_drift":
        ("counter", "", "canary probes whose temp=0 output diverged "
                        "from the pinned expected tokens: bitwise "
                        "correctness alert"),
    "tfos_slo_attrib_router_overhead_seconds":
        ("histogram", "", "per-request seconds attributed to router "
                          "work (dispatch minus upstream residency)"),
    "tfos_slo_attrib_queue_wait_seconds":
        ("histogram", "", "per-request seconds attributed to the "
                          "engine admission queue"),
    "tfos_slo_attrib_admission_seconds":
        ("histogram", "", "per-request seconds inside the engine "
                          "request span not covered by a deeper stage "
                          "(scheduler bookkeeping)"),
    "tfos_slo_attrib_prefill_seconds":
        ("histogram", "", "per-request seconds attributed to prefill"),
    "tfos_slo_attrib_kv_ship_seconds":
        ("histogram", "", "per-request seconds attributed to KV-block "
                          "pack/ship/splice (disaggregated path)"),
    "tfos_slo_attrib_decode_seconds":
        ("histogram", "", "per-request seconds attributed to decode "
                          "slot residency"),
    "tfos_slo_attrib_preempted_seconds":
        ("histogram", "", "per-request seconds spent evicted between "
                          "preemption and re-admission"),
    "tfos_slo_attrib_hedge_wait_seconds":
        ("histogram", "", "per-request seconds where two upstream "
                          "attempts raced (hedge launched, winner "
                          "undecided)"),
}


class Histogram(object):
    """Fixed log-bucket latency histogram with ``quantile(q)``.

    Buckets are geometric: bounds ``lo * growth**i`` for ``i`` in
    ``range(n)`` plus a +Inf overflow, so relative quantile error is
    bounded by ``growth`` (the bucket resolution) across the whole
    range — the property that lets one fixed layout serve microsecond
    decode steps and minute-long drains alike. Defaults: 100us .. ~1h
    at sqrt(2) growth = 52 buckets of int, a few hundred bytes.

    Single-writer convention like :class:`Counters`: the owning
    scheduler thread observes; readers take snapshots / quantiles, and
    the unlocked int adds are benign under the GIL. Observations
    outside the range clamp into the edge buckets; exact ``min``/
    ``max`` are tracked so clamped tails still report honestly.
    """

    __slots__ = ("lo", "growth", "_bounds", "_counts", "_sum", "_n",
                 "_min", "_max", "_exemplars")

    def __init__(self, lo=1e-4, hi=3600.0, growth=math.sqrt(2.0)):
        self.lo = float(lo)
        self.growth = float(growth)
        n = int(math.ceil(math.log(float(hi) / self.lo)
                          / math.log(self.growth))) + 1
        self._bounds = [self.lo * self.growth ** i for i in range(n)]
        self._counts = [0] * (n + 1)  # +1: the +Inf overflow bucket
        self._sum = 0.0
        self._n = 0
        self._min = None
        self._max = None
        # bucket index -> (trace_id, value): the LAST traced sample per
        # bucket, emitted as an OpenMetrics exemplar so a scraped p99
        # bucket links straight to a loadable trace
        self._exemplars = {}

    def observe(self, value, trace=None):
        """Record one sample (seconds); ``trace`` attaches the trace id
        as that bucket's exemplar."""
        value = float(value)
        self._sum += value
        self._n += 1
        if self._min is None or value < self._min:
            self._min = value
        if self._max is None or value > self._max:
            self._max = value
        if value <= self._bounds[0]:
            i = 0
        elif value > self._bounds[-1]:
            i = len(self._counts) - 1
        else:
            # log-position, then the forward scan only to absorb float
            # edge error: O(1) in practice
            i = int(math.log(value / self.lo) / math.log(self.growth))
            i = max(0, min(i, len(self._bounds) - 1))
            while self._bounds[i] < value:
                i += 1
        self._counts[i] += 1
        if trace:
            self._exemplars[i] = (int(trace), value)

    @property
    def count(self):
        return self._n

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Approximate q-quantile (seconds); None when empty. Error is
        bounded by one bucket (a factor of ``growth``): the returned
        value log-interpolates within the quantile's bucket and clamps
        to the observed min/max, so degenerate single-value
        distributions come back exact."""
        if not self._n:
            return None
        q = float(q)
        if q <= 0.0:
            return self._min
        if q >= 1.0:
            return self._max
        rank = max(1, int(math.ceil(q * self._n)))
        cum = 0
        for i, c in enumerate(self._counts):
            if not c:
                continue
            if cum + c >= rank:
                if i == len(self._bounds):  # overflow bucket
                    value = self._max
                else:
                    upper = self._bounds[i]
                    lower = upper / self.growth
                    frac = (rank - cum) / float(c)
                    value = lower * self.growth ** frac
                return min(max(value, self._min), self._max)
            cum += c
        return self._max

    def snapshot(self):
        """Compact JSON-able state (mergeable via
        :func:`merge_snapshots` when the layouts match)."""
        snap = {"lo": self.lo, "growth": self.growth,
                "counts": list(self._counts),
                "sum": self._sum, "n": self._n,
                "min": self._min, "max": self._max}
        if self._exemplars:
            snap["exemplars"] = {i: list(ex)
                                 for i, ex in self._exemplars.items()}
        return snap


def snapshot_quantile(snap, q):
    """Approximate q-quantile from a :meth:`Histogram.snapshot` dict —
    the same bucket math as :meth:`Histogram.quantile`, usable on
    snapshots that crossed the BEAT wire (the autoscaler prices a
    replica's TTFT p99 from its lease-carried snapshot without
    reconstructing a Histogram). None when the snapshot is empty or
    malformed."""
    try:
        n = int(snap["n"])
        counts = snap["counts"]
        lo, growth = float(snap["lo"]), float(snap["growth"])
        smin, smax = snap.get("min"), snap.get("max")
    except (TypeError, KeyError, ValueError):
        return None
    if not n:
        return None
    q = float(q)
    if q <= 0.0:
        return smin
    if q >= 1.0:
        return smax
    rank = max(1, int(math.ceil(q * n)))
    cum = 0
    n_bounds = len(counts) - 1
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            if i == n_bounds:  # overflow bucket
                value = smax
            else:
                upper = lo * growth ** i
                lower = upper / growth
                value = lower * growth ** ((rank - cum) / float(c))
            if smin is not None:
                value = max(value, smin)
            if smax is not None:
                value = min(value, smax)
            return value
        cum += c
    return smax


def _fmt(value):
    """OpenMetrics sample value: ints verbatim, floats shortest-round."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def _labels(pairs):
    if not pairs:
        return ""
    return "{" + ",".join('{}="{}"'.format(k, v) for k, v in pairs) + "}"


class MetricsRegistry(object):
    """Named home for one plane's Counters / StageTimers / Histograms.

    Three jobs:

    - :meth:`render` — OpenMetrics text exposition (``GET /metrics``):
      every registered metric under a stable, cataloged family name
      (see :data:`METRIC_FAMILIES`), terminated with ``# EOF``.
    - :meth:`snapshot` — the compact JSON-able form executors piggyback
      on BEAT heartbeat leases; :func:`merge_snapshots` folds many into
      a cluster rollup.
    - lookup — ``histogram(name)`` creates-or-returns, so bench.py and
      the profile scripts read p50/p95/p99 from the same instances the
      engine writes (no private sample lists).

    Registration is idempotent by name (a respawned engine re-adds the
    same shared objects).
    """

    def __init__(self):
        self._counters = {}   # prefix -> Counters
        self._timers = {}     # family stem -> StageTimers
        self._hists = {}      # family -> Histogram
        self._hooks = []      # zero-arg callables run before snapshot

    # -- registration / lookup -------------------------------------------

    def add_hook(self, fn):
        """Register a zero-arg callable run before every
        :meth:`snapshot` (and therefore every :meth:`render`): the
        sync point for values that live outside the registered objects
        — a FlightRecorder's ``dropped`` tally mirrored into a
        counter, a goodput ledger charging its open interval — so a
        scrape or BEAT-carried snapshot is current, not
        last-event-stale. Hooks must be cheap and never raise
        (failures are logged and swallowed). Idempotent per callable."""
        if fn not in self._hooks:
            self._hooks.append(fn)
        return fn

    def add_counters(self, prefix, counters):
        """Expose ``counters`` as ``<prefix>_<key>`` families: counts
        render as ``<prefix>_<key>_total`` counters, gauges as plain
        ``<prefix>_<key>`` gauges."""
        self._counters[prefix] = counters
        return counters

    def add_timers(self, stem, timers):
        """Expose ``timers`` as two stage-labeled counter families:
        ``<stem>_seconds_total{stage=...}`` and
        ``<stem>_samples_total{stage=...}``."""
        self._timers[stem] = timers
        return timers

    def histogram(self, family, **kwargs):
        """Create-or-return the histogram registered as ``family``."""
        hist = self._hists.get(family)
        if hist is None:
            hist = self._hists[family] = Histogram(**kwargs)
        return hist

    def get_histogram(self, family):
        return self._hists.get(family)

    # -- exposition -------------------------------------------------------

    def render(self, extra_labels=()):
        """OpenMetrics text of everything registered (ends ``# EOF``).

        ``extra_labels``: (key, value) pairs stamped on every sample —
        how the driver's cluster endpoint renders per-executor series
        from beat-carried snapshots under one family name."""
        return render_snapshot(self.snapshot(),
                               extra_labels=extra_labels)

    def snapshot(self):
        """Compact JSON-able state: {"counters": {prefix: ...},
        "timers": {stem: {"t": ..., "n": ...}}, "hists": {family: ...}}.
        Safe to ship over the JSON reservation wire (BEAT payloads)."""
        for hook in self._hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 - exposition must survive
                logger.debug("registry snapshot hook failed",
                             exc_info=True)
        return {
            "counters": {p: c.snapshot()
                         for p, c in self._counters.items()},
            "timers": {s: {"t": t.snapshot(), "n": t.counts()}
                       for s, t in self._timers.items()},
            "hists": {f: h.snapshot() for f, h in self._hists.items()},
        }


def render_snapshot(snapshot, extra_labels=()):
    """OpenMetrics text from a :meth:`MetricsRegistry.snapshot` dict.

    Shared by live registries (``MetricsRegistry.render``) and the
    driver-side cluster endpoint, which renders snapshots that crossed
    the BEAT wire. Families render in sorted order; output ends with
    the OpenMetrics ``# EOF`` terminator.
    """
    return _render([(tuple(extra_labels), snapshot)])


def _render(labeled_snapshots):
    """OpenMetrics text for many (labels, snapshot) pairs: each family
    appears ONCE (the grammar's rule), carrying one labeled sample set
    per snapshot — how N executors' beat-carried snapshots expose as N
    ``executor``-labeled series under shared family names."""
    lines = []

    def _family(name, ftype):
        meta = METRIC_FAMILIES.get(name)
        lines.append("# TYPE {} {}".format(name, ftype))
        if meta and meta[2]:
            lines.append("# HELP {} {}".format(name, meta[2]))

    def _union(section, *path):
        keys = set()
        for _, snapshot in labeled_snapshots:
            node = snapshot.get(section) or {}
            for p in path:
                node = node.get(p, {}) if isinstance(node, dict) else {}
            keys |= set(node)
        return sorted(keys)

    for prefix in _union("counters"):
        for key in _union("counters", prefix, "counts"):
            name = "{}_{}".format(prefix, key)
            _family(name, "counter")
            for extra, snapshot in labeled_snapshots:
                counts = (snapshot.get("counters", {}).get(prefix) or
                          {}).get("counts") or {}
                if key in counts:
                    lines.append("{}_total{} {}".format(
                        name, _labels(extra), _fmt(counts[key])))
        for key in _union("counters", prefix, "gauges"):
            name = "{}_{}".format(prefix, key)
            _family(name, "gauge")
            for extra, snapshot in labeled_snapshots:
                gauges = (snapshot.get("counters", {}).get(prefix) or
                          {}).get("gauges") or {}
                if key in gauges:
                    lines.append("{}{} {}".format(
                        name, _labels(extra), _fmt(gauges[key])))
    for stem in _union("timers"):
        for suffix, part in (("seconds", "t"), ("samples", "n")):
            name = "{}_{}".format(stem, suffix)
            _family(name, "counter")
            for extra, snapshot in labeled_snapshots:
                values = (snapshot.get("timers", {}).get(stem) or
                          {}).get(part) or {}
                for stage in sorted(values):
                    lines.append("{}_total{} {}".format(
                        name, _labels((("stage", stage),) + extra),
                        _fmt(values[stage])))
    for family in _union("hists"):
        _family(family, "histogram")
        for extra, snapshot in labeled_snapshots:
            snap = (snapshot.get("hists") or {}).get(family)
            if snap is None:
                continue
            bounds = [snap["lo"] * snap["growth"] ** i
                      for i in range(len(snap["counts"]) - 1)]
            # exemplar keys arrive as ints locally but as strings after
            # a JSON round-trip (beat wire); normalise once
            exemplars = {int(k): v for k, v in
                         (snap.get("exemplars") or {}).items()}

            def _exemplar(index):
                ex = exemplars.get(index)
                if not ex:
                    return ""
                return ' # {{trace_id="{}"}} {}'.format(
                    ex[0], _fmt(ex[1]))

            cum = 0
            for i, (bound, count) in enumerate(zip(bounds,
                                                   snap["counts"])):
                cum += count
                lines.append("{}_bucket{} {}{}".format(
                    family,
                    _labels((("le", "{:.6g}".format(bound)),) + extra),
                    cum, _exemplar(i)))
            lines.append("{}_bucket{} {}{}".format(
                family, _labels((("le", "+Inf"),) + extra),
                cum + snap["counts"][-1],
                _exemplar(len(snap["counts"]) - 1)))
            lines.append("{}_sum{} {}".format(
                family, _labels(extra), _fmt(snap["sum"])))
            lines.append("{}_count{} {}".format(
                family, _labels(extra), _fmt(snap["n"])))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_labeled(labeled_snapshots):
    """OpenMetrics text for many ``(label_pairs, snapshot)`` sets under
    the one grammar-correct multi-snapshot core (each family appears
    ONCE, carrying one labeled sample set per snapshot). How the fleet
    router exposes its own registry plus every replica's beat-carried
    engine snapshot as ``replica``-labeled series in a single
    document."""
    return _render([(tuple(labels), snap)
                    for labels, snap in labeled_snapshots])


def merge_snapshots(snapshots):
    """Fold many :meth:`MetricsRegistry.snapshot` dicts into one rollup.

    Counts, gauges, timer totals, and histogram buckets SUM (a gauge
    sum is the cluster-wide total — queue depth across replicas, slots
    occupied across engines); histogram layouts must match to merge
    (mismatched layouts keep the first and log). The cluster view
    ``cluster.metrics()`` returns is built from this.
    """
    out = {"counters": {}, "timers": {}, "hists": {}}
    for snap in snapshots:
        if not snap:
            continue
        for prefix, c in (snap.get("counters") or {}).items():
            dst = out["counters"].setdefault(
                prefix, {"counts": {}, "gauges": {}})
            for k, v in (c.get("counts") or {}).items():
                dst["counts"][k] = dst["counts"].get(k, 0) + v
            for k, v in (c.get("gauges") or {}).items():
                dst["gauges"][k] = dst["gauges"].get(k, 0) + v
        for stem, t in (snap.get("timers") or {}).items():
            dst = out["timers"].setdefault(stem, {"t": {}, "n": {}})
            for k, v in (t.get("t") or {}).items():
                dst["t"][k] = dst["t"].get(k, 0.0) + v
            for k, v in (t.get("n") or {}).items():
                dst["n"][k] = dst["n"].get(k, 0) + v
        for family, h in (snap.get("hists") or {}).items():
            dst = out["hists"].get(family)
            if dst is None:
                out["hists"][family] = {
                    k: (list(v) if isinstance(v, list) else v)
                    for k, v in h.items()}
                continue
            if (dst["lo"], dst["growth"], len(dst["counts"])) != \
                    (h["lo"], h["growth"], len(h["counts"])):
                logger.warning("histogram %s layouts differ; keeping "
                               "the first snapshot's", family)
                continue
            dst["counts"] = [a + b for a, b in zip(dst["counts"],
                                                   h["counts"])]
            dst["sum"] += h["sum"]
            dst["n"] += h["n"]
            for k, pick in (("min", min), ("max", max)):
                if h.get(k) is not None:
                    dst[k] = h[k] if dst.get(k) is None \
                        else pick(dst[k], h[k])
    return out


def cluster_rollup(per_executor):
    """{eid: lease-ish view} -> the ``cluster.metrics()`` shape:
    ``{"executors": per_executor, "cluster": {executors, train_step,
    merged}}`` where ``merged`` sums every executor's beat-carried
    registry snapshot (:func:`merge_snapshots`)."""
    return {
        "executors": per_executor,
        "cluster": {
            "executors": len(per_executor),
            "train_step": {eid: view.get("train_step")
                           for eid, view in per_executor.items()},
            "merged": merge_snapshots(
                [view.get("metrics") for view in per_executor.values()]),
        },
    }


def render_cluster(per_executor, cluster_gauges=None):
    """OpenMetrics text for the driver-side cluster endpoint: the
    cluster gauges plus every executor's snapshot re-rendered under an
    ``executor`` label (one family, N labeled series — the shape a
    Prometheus scrape aggregates itself). ``cluster_gauges`` adds
    server-level gauge families ({family: value} — the elastic-resize
    width gauges ride this)."""
    lines = ["# TYPE tfos_cluster_executors gauge",
             "tfos_cluster_executors {}".format(len(per_executor))]
    for family in sorted(cluster_gauges or {}):
        lines.append("# TYPE {} gauge".format(family))
        lines.append("{} {}".format(family,
                                    _fmt(cluster_gauges[family])))
    for name, key in (("tfos_cluster_train_step", "train_step"),
                      ("tfos_cluster_feed_hb_batches", "feed_hb"),
                      ("tfos_cluster_lease_age_seconds", "age"),
                      # goodput plane: per-executor step-time skew vs
                      # the fleet median (goodput.attach_step_skew
                      # annotates the views before this render)
                      ("tfos_train_step_skew", "step_skew")):
        samples = [(eid, view.get(key))
                   for eid, view in sorted(per_executor.items())
                   if view.get(key) is not None]
        if not samples:
            continue
        lines.append("# TYPE {} gauge".format(name))
        for eid, value in samples:
            lines.append("{}{} {}".format(
                name, _labels((("executor", eid),)), _fmt(value)))
    body = "\n".join(lines) + "\n"
    labeled = [((("executor", eid),), view["metrics"])
               for eid, view in sorted(per_executor.items())
               if view.get("metrics")]
    if labeled:
        body += _render(labeled).replace("# EOF\n", "")
    return body + "# EOF\n"


#: process-wide monotonic trace-id source (serving request timelines)
_TRACE_IDS = itertools.count(1)


def next_trace_id():
    """Fresh per-process trace id (int) for one request's span tree."""
    return next(_TRACE_IDS)


def mint_trace_id():
    """Fresh trace id for CROSS-PROCESS propagation (the fleet
    router's ``X-TFOS-Trace`` header): the local counter offset by a
    pid-derived high field, so a router-minted id adopted by a replica
    engine is vanishingly unlikely to collide with the replica's own
    locally-assigned ids (collisions are cosmetic — two requests
    sharing a Perfetto row — but a router that mints thousands should
    not alias replica-local rows systematically). The +1 keeps the
    salt NON-ZERO even when ``pid % 2048 == 0`` — a zero salt would
    make every minted id collide with the local ``next_trace_id``
    sequence, exactly the aliasing this exists to prevent. Stays an
    int: Chrome-trace ``tid`` fields must be numeric."""
    return (((os.getpid() & 0x7FF) + 1) << 20) \
        | (next(_TRACE_IDS) & 0xFFFFF)


class FlightRecorder(object):
    """Bounded ring of span events — the serving plane's black box.

    Every serving request gets a trace id at admission; the engine
    lands its span events (admit -> queue -> prefill -> decode ->
    finish/evict/shed) here, and :meth:`chrome_trace` renders the ring
    as Chrome trace-event JSON that loads directly in Perfetto /
    chrome://tracing (``GET /debug/trace``, scripts/trace_dump.py).
    Supervision milestones mirror in as instant events (EventLog), so
    the tail a Supervisor dumps into incident evidence reads as one
    interleaved timeline.

    Ring semantics: ``capacity`` most recent events are kept (default
    4096); overflow evicts oldest and counts into :attr:`dropped` —
    recording is always O(1) and memory is bounded no matter how long
    the process serves. Thread-safe appends (scheduler thread, HTTP
    handlers, and the supervisor all write).
    """

    def __init__(self, capacity=4096):
        self._events = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.dropped = 0
        #: trace epoch: ts fields are microseconds since this instant
        self.epoch = time.monotonic()
        #: the wall-clock time of ``epoch`` — what lets two processes'
        #: dumps be stitched onto one timeline (:func:`stitch_traces`):
        #: monotonic clocks have per-process zero points, wall clocks
        #: share one (to host clock sync)
        self.epoch_wall = time.time() - (time.monotonic() - self.epoch)

    def _append(self, event):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(event)

    def _ts(self, t):
        return int((t - self.epoch) * 1e6)

    @staticmethod
    def _clean(args):
        """Chrome-trace args must be JSON-able; coerce anything exotic
        (an exception object in an evict arg, say) to str."""
        out = {}
        for k, v in args.items():
            if isinstance(v, (str, int, float, bool, type(None))):
                out[k] = v
            elif isinstance(v, (list, tuple)):
                out[k] = [x if isinstance(x, (str, int, float, bool,
                                              type(None))) else str(x)
                          for x in v]
            else:
                out[k] = str(v)
        return out

    def span(self, name, t0, t1, trace=0, **args):
        """One complete ('X') span: [t0, t1] monotonic seconds, on the
        row of request ``trace`` (tid). Returns the event dict."""
        event = {"name": name, "ph": "X", "ts": self._ts(t0),
                 "dur": max(self._ts(t1) - self._ts(t0), 0),
                 "pid": os.getpid(), "tid": int(trace),
                 "args": self._clean(args)}
        self._append(event)
        return event

    def instant(self, name, trace=0, **args):
        """One instant ('i') event at now, on ``trace``'s row."""
        event = {"name": name, "ph": "i", "s": "t",
                 "ts": self._ts(time.monotonic()),
                 "pid": os.getpid(), "tid": int(trace),
                 "args": self._clean(args)}
        self._append(event)
        return event

    def events(self):
        with self._lock:
            return list(self._events)

    def tail(self, n=64):
        """Most recent ``n`` events, oldest first — the incident dump
        the Supervisor attaches to failure evidence."""
        with self._lock:
            events = list(self._events)
        return events[-int(n):]

    def clear(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def chrome_trace(self, events=None):
        """{"traceEvents": [...]} — the Chrome trace-event JSON object
        Perfetto loads. Adds thread_name metadata so each request's
        trace id renders as a labeled row."""
        events = self.events() if events is None else list(events)
        pid = os.getpid()
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "ts": 0, "args": {"name": "tfos"}}]
        for tid in sorted({e["tid"] for e in events}):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "ts": 0,
                         "args": {"name": "engine" if tid == 0
                                  else "request {}".format(tid)}})
        # epochWall/dropped: top-level metadata Perfetto ignores but
        # stitch_traces (cross-process timeline alignment) and the
        # router's /debug/trace saturation header read
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "epochWall": self.epoch_wall, "dropped": self.dropped}


def expose_flight_drops(registry, flight):
    """Surface ``flight.dropped`` — span events the bounded ring
    evicted — as the ``tfos_trace_spans_dropped`` counter family on
    ``registry``: a snapshot hook mirrors the live tally, so every
    scrape (and every BEAT-carried snapshot) reports ring saturation
    instead of losing spans silently. Returns the backing Counters."""
    counters = registry.add_counters(
        "tfos_trace", registry._counters.get("tfos_trace") or Counters())
    # ONE hook per registry, summing over every ring ever exposed on
    # it: re-exposure of a known ring is a no-op (a respawned engine
    # shares registry AND ring, and a fresh closure per respawn would
    # defeat add_hook's identity check — N restarts would pile up N
    # dead-engine hooks), while genuinely distinct rings accumulate
    # instead of last-write-wins clobbering each other's tally
    sources = getattr(registry, "_flight_drop_sources", None)
    if sources is None:
        sources = registry._flight_drop_sources = []

        def _sync():
            counters.set_count("spans_dropped",
                               sum(f.dropped for f in sources))

        registry.add_hook(_sync)
    if not any(f is flight for f in sources):
        sources.append(flight)
    return counters


def stitch_traces(labeled_docs):
    """Fold several ``chrome_trace`` documents — typically from
    DIFFERENT processes (a fleet router + its replicas) — into one
    Perfetto-loadable timeline.

    ``labeled_docs``: [(label, doc)] pairs. Each source becomes its own
    Chrome-trace PROCESS (synthetic pid = source index, process_name =
    label) — in-process fleets share a real pid, and distinct synthetic
    pids keep each source's rows grouped under its label either way.
    Timestamps are aligned onto the FIRST doc's epoch via each doc's
    ``epochWall`` (docs without one pass through unshifted), so a
    request that failed over between replicas reads as one causal
    timeline: its spans share a trace id (tid) across sources.

    Returns {"traceEvents": [...], "displayTimeUnit": "ms",
    "dropped": {label: n}} — ``dropped`` carries each source ring's
    eviction tally (the saturation signal ``X-TFOS-Trace-Dropped``
    sums)."""
    out = []
    dropped = {}
    base_wall = None
    for label, doc in labeled_docs:
        wall = doc.get("epochWall")
        if base_wall is None and wall is not None:
            base_wall = wall
    for idx, (label, doc) in enumerate(labeled_docs):
        wall = doc.get("epochWall")
        shift = 0 if wall is None or base_wall is None \
            else int((wall - base_wall) * 1e6)
        dropped[str(label)] = int(doc.get("dropped") or 0)
        out.append({"name": "process_name", "ph": "M", "pid": idx,
                    "tid": 0, "ts": 0, "args": {"name": str(label)}})
        for event in doc.get("traceEvents") or ():
            event = dict(event)
            event["pid"] = idx
            if event.get("ph") != "M":
                event["ts"] = int(event.get("ts", 0)) + shift
            elif event.get("name") == "process_name":
                continue  # replaced by the labeled row above
            out.append(event)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "dropped": dropped}


_FLIGHT = FlightRecorder()


def flight_recorder():
    """The process-global :class:`FlightRecorder` — the default black
    box every plane shares unless handed its own instance."""
    return _FLIGHT


class _StageSpan(object):
    __slots__ = ("_timers", "_stage", "_t0")

    def __init__(self, timers, stage):
        self._timers = timers
        self._stage = stage

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._timers.add(self._stage, time.monotonic() - self._t0)


#: port of this process's already-started jax profiler server, if any
_PROFILER_PORT = None


def start_profiler_server(port=9012):
    """Start the jax profiler gRPC server on this host (idempotent).

    jax allows exactly one profiler server per process; a second
    ``start_server`` raises. Rather than leaning on that error path,
    the started port is remembered per-process and returned on
    re-call — so framework layers and user code can both call this
    without coordinating (the caller gets the LIVE port either way,
    even if it asked for a different one). Returns None only when the
    first start genuinely fails."""
    global _PROFILER_PORT
    if _PROFILER_PORT is not None:
        if _PROFILER_PORT != port:
            logger.info("profiler server already on port %d; ignoring "
                        "request for %d", _PROFILER_PORT, port)
        return _PROFILER_PORT
    import jax

    try:
        jax.profiler.start_server(port)
        logger.info("jax profiler server on port %d", port)
        _PROFILER_PORT = port
        return port
    except Exception as e:  # noqa: BLE001 - profiling is best-effort
        logger.warning("profiler server failed to start: %s", e)
        return None


class trace(object):
    """``with tracing.trace(log_dir):`` captures a device trace window."""

    def __init__(self, log_dir):
        self.log_dir = log_dir

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.log_dir)
        return self

    def __exit__(self, *exc):
        import jax

        jax.profiler.stop_trace()


class SummaryWriter(object):
    """TensorBoard scalar writer (tf.summary backend, graceful no-op)."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        try:
            import tensorflow as tf

            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf
        except Exception:  # noqa: BLE001
            logger.warning("tensorflow unavailable: summaries disabled")
            self._writer = None

    def scalar(self, tag, value, step):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.scalar(tag, float(value), step=int(step))

    def text(self, tag, value, step):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.text(tag, str(value), step=int(step))

    def flush(self):
        if self._writer is not None:
            self._writer.flush()

    def close(self):
        if self._writer is not None:
            self._writer.close()


def metrics_hook(writer, every_steps=10, examples_per_step=None):
    """train_loop hook: loss + steps/sec (+ examples/sec) to
    TensorBoard — plus the process goodput ratio (goodput.py) whenever
    the ledger has accounted anything, so existing training logs carry
    productive-time attribution with zero caller changes."""
    state = {"t0": time.monotonic(), "last": 0}

    def _hook(step_no, train_state, metrics):
        if step_no % every_steps:
            return
        now = time.monotonic()
        dsteps = step_no - state["last"]
        dt = max(now - state["t0"], 1e-9)
        writer.scalar("train/loss", float(metrics["loss"]), step_no)
        writer.scalar("train/steps_per_sec", dsteps / dt, step_no)
        if examples_per_step:
            writer.scalar("train/examples_per_sec",
                          dsteps * examples_per_step / dt, step_no)
        try:
            from tensorflowonspark_tpu import goodput
            report = goodput.ledger().report()
            if report["productive_s"] > 0:
                writer.scalar("train/goodput_ratio",
                              report["goodput_ratio"], step_no)
        except Exception:  # noqa: BLE001 - accounting is best-effort
            logger.debug("goodput scalar failed", exc_info=True)
        writer.flush()
        state["t0"], state["last"] = now, step_no

    return _hook
