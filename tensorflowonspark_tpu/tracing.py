"""Tracing / profiling / metrics hookup.

Reference posture (SURVEY.md §5 "Tracing/profiling"): the reference only
wires TensorBoard (subprocess on one node) and leaves summaries to user
code; its own plumbing is unobservable. Here the framework exposes:

- :func:`start_profiler_server` — per-host ``jax.profiler`` server, so
  TensorBoard's profile plugin (or ``xprof``) can capture device traces.
- :func:`trace` — context manager around ``jax.profiler.trace`` for
  programmatic capture windows.
- :class:`SummaryWriter` — scalar/text summaries for TensorBoard, backed
  by the installed TF's ``tf.summary`` (CPU TF is in the image); no-ops
  cleanly when TF is absent.
- :func:`metrics_hook` — a ``Trainer.train_loop`` hook writing loss +
  step rate, the part the reference couldn't see (queue-fed step timing).
- :class:`StageTimers` — named wall-clock accumulators for the feed
  plane's per-stage breakdown (ring wait / decode / gather /
  device_put): DataFeed and infeed.prefetch share one instance so the
  whole host-side feed cost of a run lands in a single snapshot, and
  bench.py / scripts/profile_fed.py surface it next to
  ``fed_frac_of_device`` — the remaining feed loss is attributed to a
  stage instead of unexplained.
- :class:`Counters` — named monotonic counters + gauges for scheduler
  loops: serving.DecodeEngine exports queue depth, slot occupancy,
  tokens-per-step, and the request-lifecycle tallies (``shed`` /
  ``cancelled`` / ``deadline_exceeded`` / ``engine_restarts``) through
  one of these; bench.py / scripts/profile_serving.py read the
  snapshots and ModelServer's /healthz serves them live.
- :class:`EventLog` — timestamped named events for the supervision plane
  (supervisor.py): failure detected, attempt torn down, cluster
  reformed, checkpoint restored, first post-restore step. The MTTR
  numbers ``bench.py recovery`` and scripts/profile_recovery.py publish
  are spans over one of these logs.
"""

import logging
import threading
import time

logger = logging.getLogger(__name__)


class StageTimers(object):
    """Named wall-clock accumulators: one entry per pipeline stage.

    Cheap enough for per-chunk use (a dict add per sample, no locks).
    The feed plane's convention is one instance per DataFeed, shared
    with the infeed prefetcher (``infeed.prefetch(..., timers=...)``);
    the prefetch staging thread is the only cross-thread writer and
    ``snapshot()`` is read at end of run, so the unlocked add is a
    benign last-sample race, never a torn total.
    """

    __slots__ = ("_t", "_n")

    def __init__(self):
        self._t = {}
        self._n = {}

    def add(self, stage, seconds):
        """Accumulate one sample for ``stage``."""
        self._t[stage] = self._t.get(stage, 0.0) + seconds
        self._n[stage] = self._n.get(stage, 0) + 1

    def timed(self, stage):
        """``with timers.timed("decode"):`` — context-manager sampling."""
        return _StageSpan(self, stage)

    def snapshot(self):
        """{stage: total_seconds} — stable copy for artifacts/logs."""
        return dict(self._t)

    def counts(self):
        """{stage: samples} — for per-sample (per-chunk/batch) math."""
        return dict(self._n)

    def per_ms(self):
        """{stage: mean milliseconds per sample} — the human-readable
        breakdown bench.py and profile_fed.py print."""
        return {k: round(v * 1000.0 / max(self._n.get(k, 1), 1), 3)
                for k, v in self._t.items()}


class Counters(object):
    """Named monotonic counters + gauges for a serving/scheduler loop.

    The feed plane's :class:`StageTimers` answers "where did the time
    go"; this answers "what did the loop do" — requests queued, slots
    occupied, tokens emitted per step. Single-writer convention (the
    owning scheduler thread); readers take :meth:`snapshot` copies, so
    the unlocked dict ops are benign under the GIL exactly like
    StageTimers' adds.
    """

    __slots__ = ("_counts", "_gauges")

    def __init__(self):
        self._counts = {}
        self._gauges = {}

    def inc(self, name, n=1):
        """Add ``n`` to monotonic counter ``name``."""
        self._counts[name] = self._counts.get(name, 0) + n

    def gauge(self, name, value):
        """Set instantaneous gauge ``name`` (e.g. queue depth)."""
        self._gauges[name] = value

    def snapshot(self):
        """{"counts": {...}, "gauges": {...}} — stable copies."""
        return {"counts": dict(self._counts), "gauges": dict(self._gauges)}

    def rate(self, numerator, denominator):
        """counts[numerator] / counts[denominator] (0 when empty) — e.g.
        ``rate("decode_tokens", "decode_steps")`` = mean decode
        occupancy per step."""
        d = self._counts.get(denominator, 0)
        return self._counts.get(numerator, 0) / d if d else 0.0


class EventLog(object):
    """Append-only timestamped event record for supervision timelines.

    Each event carries both clocks: ``t`` (monotonic — span math) and
    ``wall`` (epoch — correlating with out-of-process evidence like a
    chaos fuse file's fire time). Thread-safe: the supervisor's monitor
    thread and the supervised-run driver loop both append.
    """

    def __init__(self):
        self._events = []
        self._lock = threading.Lock()

    def record(self, name, **detail):
        """Append one event; returns its dict (already stamped)."""
        event = {"name": name, "t": time.monotonic(), "wall": time.time()}
        if detail:
            event.update(detail)
        with self._lock:
            self._events.append(event)
        logger.debug("event %s %s", name, detail)
        return event

    def events(self, name=None):
        """All events (or those named ``name``), oldest first."""
        with self._lock:
            events = list(self._events)
        if name is not None:
            events = [e for e in events if e["name"] == name]
        return events

    def last(self, name, **match):
        """Most recent event named ``name`` whose fields match, or None."""
        for event in reversed(self.events(name)):
            if all(event.get(k) == v for k, v in match.items()):
                return event
        return None

    def span(self, from_name, to_name, **match):
        """Seconds between the last matching ``from_name`` and the first
        matching ``to_name`` at or after it; None when either is absent.
        The from/to pairing is how MTTR stages (detect -> reform ->
        restore -> first step) are extracted from one log."""
        start = self.last(from_name, **match)
        if start is None:
            return None
        for event in self.events(to_name):
            if event["t"] >= start["t"] and \
                    all(event.get(k) == v for k, v in match.items()):
                return event["t"] - start["t"]
        return None


class _StageSpan(object):
    __slots__ = ("_timers", "_stage", "_t0")

    def __init__(self, timers, stage):
        self._timers = timers
        self._stage = stage

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._timers.add(self._stage, time.monotonic() - self._t0)


def start_profiler_server(port=9012):
    """Start the jax profiler gRPC server on this host (idempotent-ish)."""
    import jax

    try:
        jax.profiler.start_server(port)
        logger.info("jax profiler server on port %d", port)
        return port
    except Exception as e:  # noqa: BLE001 - profiling is best-effort
        logger.warning("profiler server failed to start: %s", e)
        return None


class trace(object):
    """``with tracing.trace(log_dir):`` captures a device trace window."""

    def __init__(self, log_dir):
        self.log_dir = log_dir

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.log_dir)
        return self

    def __exit__(self, *exc):
        import jax

        jax.profiler.stop_trace()


class SummaryWriter(object):
    """TensorBoard scalar writer (tf.summary backend, graceful no-op)."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        try:
            import tensorflow as tf

            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf
        except Exception:  # noqa: BLE001
            logger.warning("tensorflow unavailable: summaries disabled")
            self._writer = None

    def scalar(self, tag, value, step):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.scalar(tag, float(value), step=int(step))

    def text(self, tag, value, step):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.text(tag, str(value), step=int(step))

    def flush(self):
        if self._writer is not None:
            self._writer.flush()

    def close(self):
        if self._writer is not None:
            self._writer.close()


def metrics_hook(writer, every_steps=10, examples_per_step=None):
    """train_loop hook: loss + steps/sec (+ examples/sec) to TensorBoard."""
    state = {"t0": time.monotonic(), "last": 0}

    def _hook(step_no, train_state, metrics):
        if step_no % every_steps:
            return
        now = time.monotonic()
        dsteps = step_no - state["last"]
        dt = max(now - state["t0"], 1e-9)
        writer.scalar("train/loss", float(metrics["loss"]), step_no)
        writer.scalar("train/steps_per_sec", dsteps / dt, step_no)
        if examples_per_step:
            writer.scalar("train/examples_per_sec",
                          dsteps * examples_per_step / dt, step_no)
        writer.flush()
        state["t0"], state["last"] = now, step_no

    return _hook
