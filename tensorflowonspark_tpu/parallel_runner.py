"""Embarrassingly-parallel single-node runs (no cluster bootstrap).

Reference: ``tensorflowonspark/TFParallel.py`` (SURVEY.md §2 "Parallel
single-node runner"): ``run(sc, map_fn, tf_args, num_executors)`` launches
N independent, non-communicating jobs via ``sc.parallelize(range(N), N)``
— e.g. sharded inference where each worker serves its slice alone.

Each task runs the user fn in a fresh subprocess so it can own the local
accelerator exactly like a cluster trainer would (the executor process
itself must stay jax-free), with ``single_node_env`` applied.
"""

import logging

logger = logging.getLogger(__name__)


def run(sc, map_fn, tf_args, num_executors):
    """Run ``map_fn(args, worker_index)`` on N executors; returns results.

    Unlike the cluster path there is no NodeContext — the fn gets its
    ordinal and whatever it returns ships back to the driver.
    """

    def _task(index, iterator):
        for _ in iterator:
            pass
        import multiprocessing
        import queue as q_mod

        from tensorflowonspark_tpu import util
        from tensorflowonspark_tpu.engine import serializer

        util.single_node_env()
        payload = serializer.dumps((map_fn, tf_args, index))
        ctx = multiprocessing.get_context("fork")
        out = ctx.Queue()
        proc = ctx.Process(target=_child_main, args=(payload, out))
        proc.start()
        # get() BEFORE join(): a child whose queued result exceeds the pipe
        # buffer can't exit until it's read (the documented mp deadlock),
        # and a failed worker's real traceback is in the queue either way.
        try:
            ok, value = out.get(timeout=2 * 3600)
        except q_mod.Empty:
            proc.join(timeout=10)
            raise RuntimeError(
                "parallel worker {} produced no result (exitcode {})"
                .format(index, proc.exitcode))
        proc.join()
        if not ok:
            raise RuntimeError("parallel worker {} failed:\n{}".format(
                index, value))
        if proc.exitcode != 0:
            raise RuntimeError(
                "parallel worker {} exited with code {}".format(
                    index, proc.exitcode))
        yield value

    rdd = sc.parallelize(range(num_executors), num_executors)
    return rdd.mapPartitionsWithIndex(_task).collect()


def _child_main(payload, out):
    from tensorflowonspark_tpu.engine import serializer

    map_fn, tf_args, index = serializer.loads(payload)
    try:
        out.put((True, map_fn(tf_args, index)))
    except BaseException:  # noqa: BLE001
        import traceback
        out.put((False, traceback.format_exc()))
        raise SystemExit(1)
