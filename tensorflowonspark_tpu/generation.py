"""Autoregressive generation over a KV cache — TPU-idiomatic decode.

The reference's inference is batch scoring only (SURVEY.md §3.3); this
is the don't-stop-at-parity decode loop for the decoder LM family
(models/decoder.py): the whole generation — prompt prefill AND sampling
— runs as two ``lax.scan``s inside ONE jit with static shapes, so XLA
compiles a single program per (batch, prompt_len, max_new) signature
and each new token costs O(1) attention against the pre-allocated
cache instead of re-running the O(S²) prefix.

    model = DecoderLM(vocab=V, ..., decode=True, max_len=TOTAL)
    out = generate(model, params, prompt, max_new_tokens=64)

``temperature=0`` is greedy; otherwise softmax sampling with the given
PRNG key. ``generate`` feeds one token per step (the flax decode-cache
contract), which makes its prefill a scan — simple and fully compiled.

For SERVING, this module also provides the slot-structured primitives
(``prefill_into_slot`` — fused multi-token, shape-bucketed — and
``decode_step`` over per-slot cursors) that serving.DecodeEngine
schedules continuously; see docs/serving.md. Both paths produce
bitwise-identical greedy outputs per sequence.
"""

import functools

import jax
import jax.numpy as jnp


def init_cache(model, batch, total_len):
    """Fresh KV cache for ``batch`` sequences of up to ``total_len``.

    Shape-only: ``jax.eval_shape`` over ``model.init`` yields the cache
    pytree structure without executing the full-length dummy forward
    (the cache starts as zeros anyway; params come from training, not
    from here).
    """
    dummy = jnp.zeros((batch, total_len), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def check_sampling_config(temperature, top_k, top_p, rng):
    """Raise ValueError on sampling configs that would serve silently
    wrong tokens (top_k=0 / top_p=0 mask EVERY logit to -inf and emit
    token 0 forever; temperature>0 without a key replays one stream).
    Shared by ``generate`` and ``serving.DecodeEngine`` so both paths
    fail loudly on the same inputs."""
    if temperature and rng is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if top_k is not None and int(top_k) < 1:
        raise ValueError("top_k must be >= 1, got {}".format(top_k))
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError("top_p must be in (0, 1], got {}".format(top_p))


def filter_logits(logits, top_k=None, top_p=None, temperature=0.0):
    """Apply top-k then nucleus filtering to ``[B, V]`` logits.

    Both filters mask by INDEX, not by value threshold: a value cutoff
    keeps every token tied with the boundary logit, which degenerates to
    a no-op on tied/uniform logits. ``top_p >= 1.0`` is an exact no-op
    by construction — the cumsum formulation would drop tail tokens once
    float32 saturates at 1.0. The nucleus keeps the smallest sorted
    prefix whose mass reaches p (the head token always survives).
    """
    rows = jnp.arange(logits.shape[0])[:, None]
    if top_k is not None:
        _, idx_k = jax.lax.top_k(logits, int(top_k))
        keep = jnp.zeros(logits.shape, bool).at[rows, idx_k].set(True)
        logits = jnp.where(keep, logits, -jnp.inf)
    if top_p is not None and top_p < 1.0:
        idx = jnp.argsort(logits, axis=-1)[:, ::-1]
        sorted_logits = jnp.take_along_axis(logits, idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits / (temperature or 1.0),
                               axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p  # mass BEFORE this token
        keep = jnp.zeros(logits.shape, bool).at[rows, idx].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def generate(model, params, prompt, max_new_tokens, temperature=0.0,
             rng=None, top_k=None, top_p=None, eos_token=None,
             pad_token=0):
    """[B, S] prompt -> [B, S + max_new_tokens] generated tokens.

    ``model`` must be a decode-mode instance (``decode=True``) whose
    ``max_len >= S + max_new_tokens``. Prompts must be REAL tokens of
    uniform length — there is no padding mask in the decode cache, so a
    padded ragged batch would silently attend its pad positions; bucket
    ragged prompts by length instead. Deterministic (greedy) when
    ``temperature == 0``; otherwise ``rng`` is required. ``top_k``
    restricts sampling to the k highest logits; ``top_p`` to the
    smallest nucleus whose probability mass reaches p (composable:
    top_k filters first). ``eos_token`` freezes a
    sequence once emitted — output positions after it become
    ``pad_token`` — with STATIC shapes (every sequence still runs
    ``max_new_tokens`` steps; finished ones just stop changing, the
    TPU-correct formulation of early stop).
    """
    if getattr(model, "kv_block_size", 0):
        # the solo path has no block allocator: a paged model's default
        # table maps every row to the scratch block, which would serve
        # garbage silently. Paged decode is the serving engine's job
        # (serving.DecodeEngine manages tables via paging.BlockPool);
        # solo generation wants the contiguous-cache twin of the model.
        raise ValueError(
            "generate() needs a contiguous-cache model "
            "(kv_block_size=0); paged KV decode runs through "
            "serving.DecodeEngine")
    prompt = jnp.asarray(prompt, jnp.int32)
    b, s = prompt.shape
    if int(max_new_tokens) < 0:
        raise ValueError(
            "max_new_tokens must be >= 0, got {}".format(max_new_tokens))
    total = s + int(max_new_tokens)
    if model.max_len < total:
        raise ValueError(
            "model.max_len={} < prompt {} + max_new_tokens {}".format(
                model.max_len, s, max_new_tokens))
    check_sampling_config(temperature, top_k, top_p, rng)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if int(max_new_tokens) == 0:
        # nothing to decode; returning the prompt keeps the output
        # contract ([B, S + N]) instead of crashing in split(rng, 0).
        # Placed AFTER the argument checks so N=0 rejects the same
        # invalid top_k/top_p/max_len calls every nonzero N does.
        return prompt
    cache = init_cache(model, b, model.max_len)

    def one_token(cache, token):
        """token [B, 1] -> (new cache, logits [B, V])."""
        logits, updated = model.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"])
        return updated["cache"], logits[:, -1, :]

    def prefill_step(carry, tok_col):
        cache, _ = carry
        cache, logits = one_token(cache, tok_col[:, None])
        return (cache, logits), None

    (cache, logits), _ = jax.lax.scan(
        prefill_step, (cache, jnp.zeros((b, model.vocab), jnp.float32)),
        prompt.T)

    def pick_frozen(logits, key, done):
        """``_pick_tokens`` (the ONE sampling implementation, shared
        with the slot path so they cannot diverge), but finished
        sequences emit pad and stay finished."""
        token = _pick_tokens(logits, key, temperature, top_k, top_p)
        if eos_token is None:
            return token, done
        token = jnp.where(done, jnp.int32(pad_token), token)
        return token, done | (token == eos_token)

    done0 = jnp.zeros((b,), bool)

    def decode_step(carry, key):
        cache, logits, done = carry
        token, done = pick_frozen(logits, key, done)
        cache, next_logits = one_token(cache, token[:, None])
        return (cache, next_logits, done), token

    # the LAST token needs no cache-advancing forward: scan N-1 steps,
    # then pick once from the carried logits (N forwards would waste one)
    keys = jax.random.split(rng, max_new_tokens)
    if max_new_tokens > 1:
        (cache, logits, done0), body_tokens = jax.lax.scan(
            decode_step, (cache, logits, done0), keys[:-1])
    else:
        body_tokens = jnp.zeros((0, b), jnp.int32)
    last, _ = pick_frozen(logits, keys[-1], done0)
    new_tokens = jnp.concatenate([body_tokens, last[None]], axis=0)
    return jnp.concatenate([prompt, new_tokens.T], axis=1)


# -- slot-structured primitives (continuous-batching decode) -----------
#
# The whole-generation ``generate``/``generate_jit`` above compiles one
# program per (batch, prompt_len, max_new) signature and runs each batch
# to completion — fine for offline jobs, the wrong shape for serving
# mixed-length traffic. The primitives below decompose generation so a
# scheduler (serving.DecodeEngine) can run ITERATION-LEVEL batching over
# a slot-structured KV cache:
#
# - ``init_cache(model, slots, total_len)`` — one cache, S independent
#   slots (rows), each with its own write cursor (models/decoder.py keeps
#   ``cache_index``/``pos_idx`` per-ROW for exactly this).
# - ``prefill_into_slot`` — run one request's prompt (padded to a shape
#   bucket) through a batch-1 mini cache, then scatter its K/V rows into
#   the engine cache at the slot index. Compiles once per BUCKET length,
#   not once per prompt length.
# - ``decode_step`` — one fixed-shape step over all S slots at their own
#   cursors. Compiles ONCE per (slots, total_len) engine config.
#
# Both jitted wrappers donate the engine cache, so the scheduler's
# steady-state loop updates the cache in place instead of copying it.


def _pick_tokens(logits, key, temperature, top_k, top_p):
    """[B, V] logits -> [B] sampled/argmax tokens — the single sampling
    implementation behind BOTH the solo path (``generate``'s
    pick_frozen) and the slot path, so they stay bitwise-identical at
    every temperature."""
    logits = filter_logits(logits, top_k=top_k, top_p=top_p,
                           temperature=temperature)
    if temperature:
        return jax.random.categorical(
            key, logits / temperature, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


#: flax cache leaves that are per-row WRITE CURSORS, not K/V storage
_CURSOR_LEAVES = ("cache_index", "pos_idx")


def _leaf_name(path):
    entry = path[-1]
    return getattr(entry, "key", None) or getattr(entry, "name", str(entry))


def _set_cursor_leaves(cache, idx):
    """Cache pytree with every per-row cursor leaf replaced by ``idx``.

    The scheduler (host) is the authority on each slot's position — a
    freed slot must NOT keep advancing its cursor while it idles, and a
    re-admitted slot restarts at its new prompt length. Overwriting the
    cursors before each step makes the device cache's own increments
    advisory, so inactive slots just re-write one stale position in
    place instead of walking off the end of the cache.

    This same discipline is what makes MID-FLIGHT EVICTION (PR 4:
    cancel / deadline, serving.DecodeEngine._evict_expired) free: an
    evicted request's slot is simply marked free on the host — no
    device-side cleanup exists or is needed, because a freed slot's
    stale K/V was already unreachable (cursor pinned, next occupant's
    prefill scatters over the full rows) and neighbors never see it.
    Eviction therefore cannot perturb concurrent sequences, which is
    why cancelled-neighbor outputs stay bitwise-identical
    (tests/test_serving_lifecycle.py pins this).
    """
    def repl(path, leaf):
        if _leaf_name(path) in _CURSOR_LEAVES:
            return idx.astype(leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(repl, cache)


def prefill_into_slot(model, params, cache, slot, tokens, true_len,
                      temperature=0.0, top_k=None, top_p=None, rng=None):
    """Prefill one request's prompt into slot ``slot`` of ``cache``.

    ``tokens`` is the prompt padded to its shape bucket ``[bucket_len]``
    (int32); ``true_len`` is the real prompt length. The prompt runs
    through a fresh batch-1 mini cache as ONE fused multi-token forward
    (models/decoder.py's prefill branch: K/V rows [0, bucket_len)
    written in one pass, each query row masked to its causal prefix —
    bitwise-identical per row to the token-by-token path), and the
    logits at position ``true_len - 1`` are captured. Pad positions
    beyond it do execute (static shapes) but their K/V is never
    visible: the slot's cursor is set to ``true_len`` and decode
    overwrites position ``true_len + k`` at step k strictly before the
    visibility mask reaches it. The mini cache's FULL rows are
    scattered into the slot, wiping any previous occupant's K/V.

    Returns ``(cache', first_token[int32 scalar])`` — the first generated
    token is picked here, from the true last-prompt-position logits, so a
    ``max_new_tokens=1`` request never needs a decode step at all.
    """
    total_len = next(
        leaf.shape[1] for path, leaf in
        jax.tree_util.tree_leaves_with_path(cache)
        if _leaf_name(path) == "cached_key")
    mini = init_cache(model, 1, total_len)
    true_len = jnp.asarray(true_len, jnp.int32)

    logits, upd = model.apply(
        {"params": params, "cache": mini}, tokens[None, :],
        mutable=["cache"])
    mini = upd["cache"]
    cap = jax.lax.dynamic_index_in_dim(
        logits, true_len - 1, axis=1, keepdims=False)
    first = _pick_tokens(cap, rng, temperature, top_k, top_p)[0]

    slot = jnp.asarray(slot, jnp.int32)

    def merge(path, big, small):
        name = _leaf_name(path)
        if name in _CURSOR_LEAVES:
            return big.at[slot].set(true_len.astype(big.dtype))
        return big.at[slot].set(small[0])

    cache = jax.tree_util.tree_map_with_path(merge, cache, mini)
    return cache, first


def decode_step(model, params, cache, tokens, idx, temperature=0.0,
                top_k=None, top_p=None, rng=None):
    """One fixed-shape decode step over every slot.

    ``tokens [S]`` is each slot's previously emitted token; ``idx [S]``
    each slot's write cursor (the scheduler's host-side copy — see
    :func:`_set_cursor_leaves`). Every slot computes (static shapes);
    the scheduler simply ignores emissions from slots it knows are free.
    Returns ``(cache', next_tokens [S])``.
    """
    cache = _set_cursor_leaves(cache, jnp.asarray(idx, jnp.int32))
    logits, upd = model.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        mutable=["cache"])
    picked = _pick_tokens(logits[:, -1, :], rng, temperature, top_k, top_p)
    return upd["cache"], picked


@functools.lru_cache(maxsize=32)
def slot_step_fns(model, temperature=0.0, top_k=None, top_p=None):
    """(jitted prefill_into_slot, jitted decode_step) for one model +
    sampling config, cache-donating, reused across engines.

    Compile-count contract (asserted in tests): the decode fn compiles
    ONCE per (slots, total_len) cache shape; the prefill fn once per
    bucket length. ``fn._cache_size()`` exposes the live program count —
    serving.DecodeEngine surfaces both via ``compile_stats()``.
    """
    prefill = jax.jit(
        lambda params, cache, slot, tokens, true_len, key:
        prefill_into_slot(model, params, cache, slot, tokens, true_len,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, rng=key),
        donate_argnums=(1,))
    decode = jax.jit(
        lambda params, cache, tokens, idx, key:
        decode_step(model, params, cache, tokens, idx,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    rng=key),
        donate_argnums=(1,))
    return prefill, decode


# -- paged-KV slot primitives (PR 8) -----------------------------------
#
# The paged siblings of ``prefill_into_slot``/``decode_step`` above,
# for models built with ``kv_block_size > 0`` (models/decoder.py): K/V
# lives in a shared block pool and each slot reaches its sequence
# through a block-table row. The model's ``attn_impl`` field selects
# the attention formulation (fused block-table kernel vs PR 8's gather
# reference — ops/paged_attention.py); since flax Modules hash by
# their fields, ``paged_step_fns``'s lru_cache keys distinct programs
# per formulation automatically. Because the POOL is batch-independent
# (only tables and cursors are per-row), prefill needs no mini cache +
# scatter-merge at all: a batch-1 apply with the slot's table row and a
# start cursor writes the tail's K/V straight into the slot's blocks —
# which is also exactly how a PREFIX-CACHED admission prefills only the
# un-shared tail of its prompt (start = shared prefix length, a block
# multiple; the fused mid-sequence continuation branch reads the shared
# prefix K/V through the table).


def _set_paged_leaves(cache, idx, tables):
    """Cache pytree with cursor leaves replaced by ``idx`` and
    ``block_table`` leaves by ``tables`` — the paged extension of
    :func:`_set_cursor_leaves`: the host scheduler is the authority on
    both position AND block mapping, every call."""
    def repl(path, leaf):
        name = _leaf_name(path)
        if name in _CURSOR_LEAVES:
            return idx.astype(leaf.dtype)
        if name == "block_table":
            return tables.astype(leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(repl, cache)


def paged_prefill_into_slot(model, params, cache, table_row, tokens,
                            tail_len, start, temperature=0.0, top_k=None,
                            top_p=None, rng=None):
    """Prefill a prompt TAIL into the pool blocks ``table_row`` maps.

    ``tokens [bucket]`` is the un-shared tail of the prompt padded to
    its shape bucket; ``tail_len`` its real length; ``start`` the
    logical position the tail begins at (0 cold, the shared-prefix
    length — always a block multiple — on a prefix-cache hit).
    ``table_row [MB]`` is the slot's full block table: shared prefix
    blocks first (read-only here: the cursor starts past them), then
    the private blocks the tail writes, then scratch (0) padding that
    absorbs bucket-pad writes.

    Runs as ONE batch-1 apply against the SHARED pool — no mini cache:
    the pool leaves are batch-independent, so the slot's writes land in
    place and no other slot's blocks are touched. Returns
    ``(cache', first_token)`` with the first generated token picked
    from the logits at the last real tail position (so a warm
    ``max_new_tokens=1`` request costs one tiny-bucket forward)."""
    table_row = jnp.asarray(table_row, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    tail_len = jnp.asarray(tail_len, jnp.int32)

    def view(path, leaf):
        name = _leaf_name(path)
        if name in _CURSOR_LEAVES:
            return jnp.full((1,), start, leaf.dtype)
        if name == "block_table":
            return table_row[None, :].astype(leaf.dtype)
        return leaf

    mini = jax.tree_util.tree_map_with_path(view, cache)
    logits, upd = model.apply(
        {"params": params, "cache": mini}, tokens[None, :],
        mutable=["cache"])
    cap = jax.lax.dynamic_index_in_dim(
        logits, tail_len - 1, axis=1, keepdims=False)
    first = _pick_tokens(cap, rng, temperature, top_k, top_p)[0]

    def merge(path, big, new):
        # pool leaves take the update; the engine-shaped [S] cursor and
        # [S, MB] table leaves keep their (host-overwritten-anyway)
        # storage so the cache pytree's shapes never change
        if _leaf_name(path) in _CURSOR_LEAVES + ("block_table",):
            return big
        return new

    cache = jax.tree_util.tree_map_with_path(merge, cache, upd["cache"])
    return cache, first


def paged_decode_step(model, params, cache, tokens, idx, tables,
                      temperature=0.0, top_k=None, top_p=None, rng=None):
    """One fixed-shape decode step over every slot, paged: identical to
    :func:`decode_step` except the host also supplies ``tables
    [S, MB]`` — each slot's block-table row — alongside the cursors."""
    cache = _set_paged_leaves(cache, jnp.asarray(idx, jnp.int32),
                              jnp.asarray(tables, jnp.int32))
    logits, upd = model.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        mutable=["cache"])
    picked = _pick_tokens(logits[:, -1, :], rng, temperature, top_k, top_p)
    return upd["cache"], picked


@functools.lru_cache(maxsize=32)
def paged_step_fns(model, temperature=0.0, top_k=None, top_p=None):
    """(jitted paged prefill, jitted paged decode) for one paged model
    + sampling config — the paged sibling of :func:`slot_step_fns`,
    same compile-count contract: ONE decode program per engine config,
    one prefill program per TAIL bucket (``start``/``tail_len`` are
    traced scalars, so a warm prefix and a cold prompt of equal tail
    bucket share a program)."""
    prefill = jax.jit(
        lambda params, cache, table_row, tokens, tail_len, start, key:
        paged_prefill_into_slot(model, params, cache, table_row, tokens,
                                tail_len, start, temperature=temperature,
                                top_k=top_k, top_p=top_p, rng=key),
        donate_argnums=(1,))
    decode = jax.jit(
        lambda params, cache, tokens, idx, tables, key:
        paged_decode_step(model, params, cache, tokens, idx, tables,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, rng=key),
        donate_argnums=(1,))
    return prefill, decode


# -- KV block-row shipping primitives (PR 17) ---------------------------
#
# The device half of prefill/decode disaggregation: a prefill worker
# exports the pool rows its blocks occupy (host-side gather — the bytes
# that go on the wire are the POOL'S OWN storage, int8 codes + float32
# scales on a quantized pool, so shipping needs no dequant round-trip
# and splice parity is bitwise by construction), and a decode worker
# scatters them into ITS pool at freshly allocated block ids. Leaves
# are keyed by their full tree path, not discovery order, so a
# structural mismatch (different layer count, missing scales) fails
# loudly instead of splicing K into V.

#: flax cache leaves that are per-BLOCK pool storage — the shippable
#: content of a paged cache (everything else is per-slot host-owned
#: state: cursors and block tables never ship)
_POOL_LEAVES = ("cached_key", "cached_value", "key_scale", "value_scale")


def _path_key(path):
    """Stable string key of one cache-leaf path (e.g.
    ``block_0/attn/cached_key``) — the wire name a shipped row set is
    keyed under, identical across processes for one model config."""
    return "/".join(
        str(getattr(e, "key", None) or getattr(e, "name", None) or e)
        for e in path)


def gather_block_rows(cache, block_ids):
    """Host-side gather of pool rows ``block_ids`` from every pool leaf.

    Returns ``[(path_key, rows)]`` in tree order, ``rows`` a numpy array
    of shape ``[len(block_ids), *leaf.shape[1:]]`` in the LEAF'S dtype —
    int8 codes stay int8, scales stay float32. One device->host copy
    per leaf; the caller (the engine's scheduler thread) must hold the
    blocks referenced so the pool cannot recycle them mid-gather."""
    import numpy as np

    ids = np.asarray(list(block_ids), np.int32)
    out = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if _leaf_name(path) in _POOL_LEAVES:
            out.append((_path_key(path), np.asarray(leaf)[ids]))
    return out


def scatter_block_rows(cache, block_ids, rows):
    """Inverse of :func:`gather_block_rows`: cache' with each shipped
    row set written at ``block_ids`` into its path-matched pool leaf.

    ``rows`` is ``{path_key: array}`` (or the gather's pair list).
    Raises ValueError on a leaf the shipment lacks or a dtype mismatch
    (an fp32 shipment cannot splice into an int8 pool — requantizing
    here would break the bitwise-parity contract; ship pools must
    match dtypes end to end)."""
    rows = dict(rows)
    ids = jnp.asarray(list(block_ids), jnp.int32)

    def repl(path, leaf):
        if _leaf_name(path) not in _POOL_LEAVES:
            return leaf
        key = _path_key(path)
        if key not in rows:
            raise ValueError(
                "shipment lacks pool leaf {!r} (incompatible model "
                "config between ship endpoints)".format(key))
        arr = rows[key]
        if str(arr.dtype) != str(leaf.dtype):
            raise ValueError(
                "shipped rows for {!r} are {} but the pool stores {} — "
                "ship endpoints must share kv_dtype".format(
                    key, arr.dtype, leaf.dtype))
        return leaf.at[ids].set(jnp.asarray(arr))

    return jax.tree_util.tree_map_with_path(repl, cache)


# -- speculative decoding primitives (PR 15) ----------------------------
#
# Draft-model speculation over the SAME paged pool discipline: a
# reduced-depth clone of the target (same vocab/embedding/head, the
# first ``num_layers_draft`` blocks, weight-tied — see
# :func:`draft_params`) proposes k tokens with k cheap single-token
# steps fused into ONE scanned program (``paged_propose_tokens``); the
# target then scores all k proposals in ONE fused multi-token apply
# (``paged_verify_step`` — the s>1 branch of models/decoder.py, i.e.
# the multi-token prefill machinery pointed at decode). Token-matching
# acceptance makes the emitted stream exactly the target's: at
# temperature=0 the verify picks ARE the plain engine's argmax chain,
# so greedy speculative output is bitwise-identical to the plain
# engine (pinned in tests/test_speculative.py); at temperature>0 every
# emitted token is still a true target-model sample (the draft token
# is only kept when it EQUALS the target's own pick at that position),
# but the PRNG stream advances differently per accepted run length, so
# sampled outputs are exact in distribution, not bitwise-reproducible
# against the plain engine — serving.DecodeEngine documents this
# honestly.
#
# The draft maintains its OWN cache pytree but shares the engine's
# HOST state — block tables and cursors — so one BlockPool governs
# both: every target write has a mirrored draft write at the same
# (block, offset), which is what keeps prefix-cache hits valid for the
# draft pool too.


def draft_params(params, num_layers_draft):
    """Weight-tied draft parameters: the target's embeddings, first
    ``num_layers_draft`` blocks, final norm, and head — the exact
    subtree a ``model.clone(num_layers=num_layers_draft)`` consumes.
    No copies: the returned dict aliases the target's arrays (tying is
    the point — no separate draft training pipeline exists, and the
    truncated-depth model is the honest zero-extra-weights draft).
    Raises KeyError-shaped ValueError on param trees that are not
    DecoderLM-family (no ``block_0``/``tok_embed`` naming)."""
    keep = {"tok_embed", "pos_embed", "ln_f", "head"}
    keep.update("block_%d" % i for i in range(int(num_layers_draft)))
    tied = {name: params[name] for name in keep if name in params}
    missing = keep - set(tied)
    if missing:
        raise ValueError(
            "params lack the DecoderLM-family entries {} needed for a "
            "weight-tied draft".format(sorted(missing)))
    return tied


def paged_propose_tokens(model, params, cache, last, idx, tables, k,
                         temperature=0.0, top_k=None, top_p=None,
                         rng=None):
    """k chained draft decode steps as ONE program: feed ``last [S]``,
    pick, feed the pick, ... — ``lax.scan`` over k single-token paged
    steps, each writing its K/V through the shared block tables at the
    advancing cursors. Returns ``(cache', drafts [S, k])`` where
    ``drafts[:, j]`` is the draft's pick after consuming the j-th fed
    token (so the fed sequence is ``[last, d_1, ..., d_{k-1}]`` and
    the proposals are ``d_1..d_k``)."""
    import jax

    cache = _set_paged_leaves(cache, jnp.asarray(idx, jnp.int32),
                              jnp.asarray(tables, jnp.int32))
    if rng is None:
        rng = jax.random.PRNGKey(0)
    keys = jax.random.split(rng, k)

    def body(carry, key):
        cache, tok = carry
        logits, upd = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            mutable=["cache"])
        picked = _pick_tokens(logits[:, -1, :], key, temperature,
                              top_k, top_p)
        return (upd["cache"], picked), picked

    (cache, _), drafts = jax.lax.scan(body, (cache, last), keys)
    return cache, drafts.T  # [k, S] -> [S, k]


def paged_verify_step(model, params, cache, tokens, idx, tables,
                      temperature=0.0, top_k=None, top_p=None,
                      rng=None):
    """Score a whole proposal window in ONE target apply: ``tokens
    [S, k]`` is ``[last, d_1, ..., d_{k-1}]`` per slot; the s=k fused
    branch writes all k K/V rows through the tables and yields logits
    at every position. Returns ``(cache', picks [S, k])`` — the
    target's own next-token choice after each fed token. Acceptance is
    the caller's (host-side) token match: ``d_{j+1}`` stands iff it
    equals ``picks[:, j]``, and ``picks[:, a]`` is the correction
    token when the match chain breaks at ``a``."""
    cache = _set_paged_leaves(cache, jnp.asarray(idx, jnp.int32),
                              jnp.asarray(tables, jnp.int32))
    logits, upd = model.apply(
        {"params": params, "cache": cache}, tokens, mutable=["cache"])
    s, k, v = logits.shape
    picked = _pick_tokens(logits.reshape(s * k, v), rng, temperature,
                          top_k, top_p)
    return upd["cache"], picked.reshape(s, k)


def paged_spec_round(model, draft_model, params, draft_params, cache,
                     draft_cache, last, idx, tables, k,
                     temperature=0.0, top_k=None, top_p=None,
                     rng=None):
    """One whole speculative round — propose THEN verify — as a single
    traceable computation: composed from :func:`paged_propose_tokens`
    and :func:`paged_verify_step` (no duplicated logic), with the
    draft's fed window wired straight into the verify feed ON DEVICE.
    Under one jit this is ONE dispatch and ONE host sync per round
    instead of two of each — on a CPU CI box the dispatch+sync is a
    real fraction of a round, and on TPU it halves launch overhead.
    Returns ``(cache', draft_cache', drafts [S, k], targets
    [S, k])``."""
    import jax

    if rng is None:
        rng = jax.random.PRNGKey(0)
    rng_d, rng_v = jax.random.split(rng)
    draft_cache, drafts = paged_propose_tokens(
        draft_model, draft_params, draft_cache, last, idx, tables, k,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng_d)
    feed = jnp.concatenate([last[:, None], drafts[:, :k - 1]], axis=1)
    cache, targets = paged_verify_step(
        model, params, cache, feed, idx, tables,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng_v)
    return cache, draft_cache, drafts, targets


@functools.lru_cache(maxsize=32)
def speculative_step_fns(model, draft_model, k, temperature=0.0,
                         top_k=None, top_p=None):
    """The jitted FUSED round fn for one (target, draft, k, sampling)
    tuple, cache-donating, reused across engines — the speculative
    sibling of :func:`paged_step_fns`. Compile-count contract: ONE
    round program per engine config (k is static; the fn is
    fixed-shape over all S slots). Call signature:
    ``fn(params, draft_params, cache, draft_cache, last, idx, tables,
    key) -> (cache', draft_cache', drafts, targets)``."""
    import jax

    return jax.jit(
        lambda params, draft_params, cache, draft_cache, last, idx, \
        tables, key:
        paged_spec_round(model, draft_model, params, draft_params,
                         cache, draft_cache, last, idx, tables,
                         int(k), temperature=temperature, top_k=top_k,
                         top_p=top_p, rng=key),
        donate_argnums=(2, 3))


@functools.lru_cache(maxsize=32)
def speculative_probe_fns(model, draft_model, k, temperature=0.0,
                          top_k=None, top_p=None):
    """NON-donating (propose, verify) jits over the same bodies the
    fused round composes — the measurement surface behind
    ``DecodeEngine.measure_spec``: the hot loop runs one fused
    program (per-op timing is invisible inside it), so the honest
    draft-vs-verify attribution runs each half standalone at live
    shapes, exactly the ``measure_attn`` pattern. Non-donating so a
    probe can run against the engine's LIVE caches without consuming
    them."""
    import jax

    propose = jax.jit(
        lambda params, cache, last, idx, tables, key:
        paged_propose_tokens(draft_model, params, cache, last, idx,
                             tables, int(k), temperature=temperature,
                             top_k=top_k, top_p=top_p, rng=key))
    verify = jax.jit(
        lambda params, cache, tokens, idx, tables, key:
        paged_verify_step(model, params, cache, tokens, idx, tables,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, rng=key))
    return propose, verify


def default_buckets(total_len, lo=8):
    """Power-of-two prompt buckets up to ``total_len``: the compile-count
    bound for prefill is ``len(default_buckets(...))`` programs."""
    buckets, b = [], max(2, int(lo))
    while b < total_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(total_len))
    return tuple(buckets)


def bucket_for(length, buckets):
    """Smallest bucket >= length (raises if the prompt outgrows them)."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        "prompt length {} exceeds the largest bucket {}".format(
            length, buckets[-1]))


@functools.lru_cache(maxsize=64)
def _jitted_generate(model, max_new_tokens, temperature, top_k, top_p,
                     eos_token, pad_token):
    # flax Modules are frozen dataclasses (hashable), so the option
    # tuple keys a REUSED jitted fn — a fresh jax.jit(lambda) per call
    # would recompile every time
    return jax.jit(
        lambda params, tokens, key: generate(
            model, params, tokens, max_new_tokens, temperature, key,
            top_k=top_k, top_p=top_p, eos_token=eos_token,
            pad_token=pad_token))


def generate_jit(model, params, prompt, max_new_tokens, temperature=0.0,
                 rng=None, top_k=None, top_p=None, eos_token=None,
                 pad_token=0):
    """jit-compiled :func:`generate`: one compile per option tuple x
    input-shape signature, cached across calls."""
    # normalize to hashable python scalars: array-typed eos_token (a
    # natural way to pass it) would crash lru_cache, and 5.0 vs 5 would
    # key two compiles of the identical program
    fn = _jitted_generate(model, int(max_new_tokens), float(temperature),
                          None if top_k is None else int(top_k),
                          None if top_p is None else float(top_p),
                          None if eos_token is None else int(eos_token),
                          int(pad_token))
    return fn(params, prompt,
              rng if rng is not None else jax.random.PRNGKey(0))
