"""Autoregressive generation over a KV cache — TPU-idiomatic decode.

The reference's inference is batch scoring only (SURVEY.md §3.3); this
is the don't-stop-at-parity decode loop for the decoder LM family
(models/decoder.py): the whole generation — prompt prefill AND sampling
— runs as two ``lax.scan``s inside ONE jit with static shapes, so XLA
compiles a single program per (batch, prompt_len, max_new) signature
and each new token costs O(1) attention against the pre-allocated
cache instead of re-running the O(S²) prefix.

    model = DecoderLM(vocab=V, ..., decode=True, max_len=TOTAL)
    out = generate(model, params, prompt, max_new_tokens=64)

``temperature=0`` is greedy; otherwise softmax sampling with the given
PRNG key. Feeding happens one token per step (the flax decode-cache
contract), which also makes prefill a scan — simple and fully
compiled; a fused multi-token prefill is a later optimization.
"""

import functools

import jax
import jax.numpy as jnp


def init_cache(model, batch, total_len):
    """Fresh KV cache for ``batch`` sequences of up to ``total_len``.

    Shape-only: ``jax.eval_shape`` over ``model.init`` yields the cache
    pytree structure without executing the full-length dummy forward
    (the cache starts as zeros anyway; params come from training, not
    from here).
    """
    dummy = jnp.zeros((batch, total_len), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def filter_logits(logits, top_k=None, top_p=None, temperature=0.0):
    """Apply top-k then nucleus filtering to ``[B, V]`` logits.

    Both filters mask by INDEX, not by value threshold: a value cutoff
    keeps every token tied with the boundary logit, which degenerates to
    a no-op on tied/uniform logits. ``top_p >= 1.0`` is an exact no-op
    by construction — the cumsum formulation would drop tail tokens once
    float32 saturates at 1.0. The nucleus keeps the smallest sorted
    prefix whose mass reaches p (the head token always survives).
    """
    rows = jnp.arange(logits.shape[0])[:, None]
    if top_k is not None:
        _, idx_k = jax.lax.top_k(logits, int(top_k))
        keep = jnp.zeros(logits.shape, bool).at[rows, idx_k].set(True)
        logits = jnp.where(keep, logits, -jnp.inf)
    if top_p is not None and top_p < 1.0:
        idx = jnp.argsort(logits, axis=-1)[:, ::-1]
        sorted_logits = jnp.take_along_axis(logits, idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits / (temperature or 1.0),
                               axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p  # mass BEFORE this token
        keep = jnp.zeros(logits.shape, bool).at[rows, idx].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def generate(model, params, prompt, max_new_tokens, temperature=0.0,
             rng=None, top_k=None, top_p=None, eos_token=None,
             pad_token=0):
    """[B, S] prompt -> [B, S + max_new_tokens] generated tokens.

    ``model`` must be a decode-mode instance (``decode=True``) whose
    ``max_len >= S + max_new_tokens``. Prompts must be REAL tokens of
    uniform length — there is no padding mask in the decode cache, so a
    padded ragged batch would silently attend its pad positions; bucket
    ragged prompts by length instead. Deterministic (greedy) when
    ``temperature == 0``; otherwise ``rng`` is required. ``top_k``
    restricts sampling to the k highest logits; ``top_p`` to the
    smallest nucleus whose probability mass reaches p (composable:
    top_k filters first). ``eos_token`` freezes a
    sequence once emitted — output positions after it become
    ``pad_token`` — with STATIC shapes (every sequence still runs
    ``max_new_tokens`` steps; finished ones just stop changing, the
    TPU-correct formulation of early stop).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, s = prompt.shape
    if int(max_new_tokens) < 0:
        raise ValueError(
            "max_new_tokens must be >= 0, got {}".format(max_new_tokens))
    total = s + int(max_new_tokens)
    if model.max_len < total:
        raise ValueError(
            "model.max_len={} < prompt {} + max_new_tokens {}".format(
                model.max_len, s, max_new_tokens))
    if temperature and rng is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if top_k is not None and int(top_k) < 1:
        raise ValueError("top_k must be >= 1, got {}".format(top_k))
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError("top_p must be in (0, 1], got {}".format(top_p))
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if int(max_new_tokens) == 0:
        # nothing to decode; returning the prompt keeps the output
        # contract ([B, S + N]) instead of crashing in split(rng, 0).
        # Placed AFTER the argument checks so N=0 rejects the same
        # invalid top_k/top_p/max_len calls every nonzero N does.
        return prompt
    cache = init_cache(model, b, model.max_len)

    def one_token(cache, token):
        """token [B, 1] -> (new cache, logits [B, V])."""
        logits, updated = model.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"])
        return updated["cache"], logits[:, -1, :]

    def prefill_step(carry, tok_col):
        cache, _ = carry
        cache, logits = one_token(cache, tok_col[:, None])
        return (cache, logits), None

    (cache, logits), _ = jax.lax.scan(
        prefill_step, (cache, jnp.zeros((b, model.vocab), jnp.float32)),
        prompt.T)

    def pick(logits, key):
        logits = filter_logits(logits, top_k=top_k, top_p=top_p,
                               temperature=temperature)
        if temperature:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def pick_frozen(logits, key, done):
        """pick(), but finished sequences emit pad and stay finished."""
        token = pick(logits, key).astype(jnp.int32)
        if eos_token is None:
            return token, done
        token = jnp.where(done, jnp.int32(pad_token), token)
        return token, done | (token == eos_token)

    done0 = jnp.zeros((b,), bool)

    def decode_step(carry, key):
        cache, logits, done = carry
        token, done = pick_frozen(logits, key, done)
        cache, next_logits = one_token(cache, token[:, None])
        return (cache, next_logits, done), token

    # the LAST token needs no cache-advancing forward: scan N-1 steps,
    # then pick once from the carried logits (N forwards would waste one)
    keys = jax.random.split(rng, max_new_tokens)
    if max_new_tokens > 1:
        (cache, logits, done0), body_tokens = jax.lax.scan(
            decode_step, (cache, logits, done0), keys[:-1])
    else:
        body_tokens = jnp.zeros((0, b), jnp.int32)
    last, _ = pick_frozen(logits, keys[-1], done0)
    new_tokens = jnp.concatenate([body_tokens, last[None]], axis=0)
    return jnp.concatenate([prompt, new_tokens.T], axis=1)


@functools.lru_cache(maxsize=64)
def _jitted_generate(model, max_new_tokens, temperature, top_k, top_p,
                     eos_token, pad_token):
    # flax Modules are frozen dataclasses (hashable), so the option
    # tuple keys a REUSED jitted fn — a fresh jax.jit(lambda) per call
    # would recompile every time
    return jax.jit(
        lambda params, tokens, key: generate(
            model, params, tokens, max_new_tokens, temperature, key,
            top_k=top_k, top_p=top_p, eos_token=eos_token,
            pad_token=pad_token))


def generate_jit(model, params, prompt, max_new_tokens, temperature=0.0,
                 rng=None, top_k=None, top_p=None, eos_token=None,
                 pad_token=0):
    """jit-compiled :func:`generate`: one compile per option tuple x
    input-shape signature, cached across calls."""
    # normalize to hashable python scalars: array-typed eos_token (a
    # natural way to pass it) would crash lru_cache, and 5.0 vs 5 would
    # key two compiles of the identical program
    fn = _jitted_generate(model, int(max_new_tokens), float(temperature),
                          None if top_k is None else int(top_k),
                          None if top_p is None else float(top_p),
                          None if eos_token is None else int(eos_token),
                          int(pad_token))
    return fn(params, prompt,
              rng if rng is not None else jax.random.PRNGKey(0))
