"""User-facing node helpers — the reference's ``TFNode`` module surface.

Reference: ``tensorflowonspark/TFNode.py`` (SURVEY.md §2 "Executor user
API"): ``DataFeed`` (re-exported from :mod:`datafeed` here),
``hdfs_path``, ``start_cluster_server``, ``export_saved_model``. Kept as a
module so reference-style user code ports with an import swap::

    from tensorflowonspark_tpu import tfnode as TFNode
    feed = TFNode.DataFeed(ctx.mgr, train_mode=True)
"""

import logging

from tensorflowonspark_tpu.datafeed import DataFeed  # noqa: F401

logger = logging.getLogger(__name__)


def hdfs_path(ctx, path):
    """Absolutize a user path against the cluster's default FS/working dir.

    Reference: ``TFNode.hdfs_path(ctx, path)``.
    """
    return ctx.absolute_path(path)


def start_cluster_server(ctx, num_devices=1, protocol=None):
    """Join the device collective; returns the local jax devices.

    Reference: TF1-era ``TFNode.start_cluster_server(ctx, num_gpus, rdma)``
    built a ``tf.train.Server`` (grpc / grpc+verbs). On TPU the transport
    is ICI/DCN managed by the runtime — ``protocol`` is accepted and
    ignored for parity — and 'starting the server' is
    ``jax.distributed.initialize`` via :meth:`NodeContext.initialize_jax`.
    """
    if protocol not in (None, "grpc"):
        logger.warning("protocol=%r has no TPU analog (ICI/DCN is runtime-"
                       "managed); ignoring", protocol)
    return ctx.initialize_jax()


def export_saved_model(export_dir, apply_fn, variables, signature=None):
    """Chief-side model export (reference: ``TFNode.export_saved_model``).

    Thin delegate to :func:`tensorflowonspark_tpu.export.save_model`.
    """
    from tensorflowonspark_tpu import export

    export.save_model(export_dir, apply_fn, variables, signature)
