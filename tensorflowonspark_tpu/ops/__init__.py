"""Custom TPU kernels (Pallas).

The reference delegated all device kernels to TensorFlow/cuDNN
(SURVEY.md §2.2); here the hot ops the XLA autofuser doesn't already win
on are hand-written Pallas kernels, with XLA reference implementations as
both fallback (non-TPU platforms) and correctness oracles in tests.
"""

from tensorflowonspark_tpu.ops.flash_attention import flash_attention  # noqa: F401
from tensorflowonspark_tpu.ops.paged_attention import paged_attention  # noqa: F401
