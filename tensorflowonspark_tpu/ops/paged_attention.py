"""Fused paged attention: attend through a block table, no gather.

The device half of the paged KV cache (PR 8) stores K/V in a shared
pool ``[pool_rows, block_size, heads, head_dim]`` per layer, with each
batch row reaching its sequence through a ``block_table`` row. PR 8's
attention was the XLA *gather* formulation: materialize the logical
``[B, L, heads, dim]`` view (``pool[table]``) every step, then attend —
resident memory is paged, but transient compute memory is not, so
per-step bandwidth scales with the table width (max context), not with
the tokens actually live.

This module is the fused formulation (PR 11): attention consumes the
pool and the block table DIRECTLY, streaming one block at a time
through the online-softmax recurrence (the flash pattern,
ops/flash_attention.py), and visiting only the blocks a row actually
occupies — per-step traffic scales with LIVE tokens. Three
implementations share one contract:

- ``impl="pallas"`` — the TPU kernel. Grid ``(B * heads, table_width)``
  under a ``PrefetchScalarGridSpec``: the block table rides scalar
  prefetch and the K/V BlockSpec *index maps* read it, so the pipeline
  DMAs exactly the pool block each grid step attends — paged
  attention as an index-mapping problem, no gather materialization.
  Dead table slots (past a row's live length) clamp their index map to
  the row's last live block: consecutive equal indices make Pallas
  skip the copy, so DMA traffic tracks live blocks, and a ``pl.when``
  guard skips their compute.
- ``impl="blockwise"`` — the same recurrence in pure ``lax`` for
  non-TPU backends (CPU tier-1): ONE ``fori_loop`` with a *traced*
  bound (the batch's deepest live block count) whose body visits one
  block per row as a whole-batch gather + matmul; rows already past
  their own depth are frozen by the mask (their update is an exact
  no-op). Never materializes the logical view; per-step transient
  work is O(B × max live blocks), not O(B × table width).
- ``impl="gather"`` — PR 8's formulation, verbatim (moved here from
  models/decoder.py so both paths live in one module). The reference
  oracle the fused paths are pinned against, and the contrast curve
  ``bench.py serving_decode.multi_turn`` publishes.

Numerics: the gather path takes one softmax over the full logical row;
the fused paths take the online (rescaled-accumulator) recurrence over
the same visible set. Identical math, different float accumulation
order — last-ulp differences, which is why the serving parity pins are
TOKEN-level at temperature=0 (tests/test_paged_kv.py, same contract as
the fused-prefill branch in models/decoder.py).

Masking contract (identical across impls): query ``i`` of row ``b``
sits at logical position ``pos[b, i]`` and attends every key position
``<= pos[b, i]``. Callers write the step's K/V through the table
BEFORE attending (models/decoder.py), so the current token sees
itself. Layout: ``q [B, S_q, N, D]``, pools
``[P, block_size, N, D]``, ``block_table [B, MB]`` int32,
``pos [B, S_q]`` int32; returns ``[B, S_q, N, D]``.

INT8 KV (PR 15): with ``k_scale``/``v_scale`` supplied, the pools hold
``int8`` codes and the scales (``[P, block_size, heads]`` float32 —
one per head per token row of each block, stored block-aligned beside
the pool) dequantize them INSIDE each formulation: the gather path
dequantizes the materialized view, the blockwise loop and the Pallas
kernel dequantize one block at a time right after its load — so the
HBM traffic a decode step pays is the int8 bytes, not the float ones
(per-step KV bandwidth halves vs bf16, quarters vs f32; the exact
follow-up PR 11 named). Quantization itself happens at WRITE time in
models/decoder.py via :func:`quantize_kv`. A per-(block, head) single
scale cannot work for an incremental decode cache — a scale-raising
write would require requantizing every code already in the block —
which is why the scales are per token row within each block.
"""

import functools

import jax
import jax.numpy as jnp


def quantize_kv(x):
    """``[..., D]`` float K/V -> ``(codes int8 [..., D], scales
    float32 [...])`` — symmetric per-head (last-axis) absmax
    quantization to 127 levels. An all-zero vector quantizes to zero
    codes under scale 1.0 (never a 0/0). EXACT round-trip contract
    (pinned in tests): ``quantize_kv(dequantize_kv(*quantize_kv(x)))``
    reproduces the codes and scales bitwise — the absmax element maps
    to ±127 exactly, so requantizing the dequantized grid is a fixed
    point. paging.BlockPool.quantize is the numpy mirror of this
    formulation (one contract, two runtimes)."""
    s = jnp.max(jnp.abs(x), axis=-1).astype(jnp.float32) / 127.0
    s = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, s


def dequantize_kv(q, s):
    """Inverse of :func:`quantize_kv`: ``codes * scales`` in float32."""
    return q.astype(jnp.float32) * s[..., None].astype(jnp.float32)


def _nblocks(pos, block_size, table_width):
    """Blocks a row actually occupies: enough to cover its highest
    visible position, clamped to the table (bucket-padded prefill rows
    can carry ``pos`` past the logical capacity; the gather view ends
    at the table too, so the clamp preserves parity)."""
    return jnp.minimum((jnp.max(pos, axis=-1) + block_size)
                       // block_size, table_width)


def _gather(q, k_pool, v_pool, block_table, pos, scale, k_scale=None,
            v_scale=None):
    """PR 8's XLA formulation, verbatim: materialize the logical
    ``[B, L, N, D]`` view through the table, one softmax over it
    (int8 pools dequantize into the materialized view — the reference
    the fused in-kernel dequant is pinned against)."""
    b, s, n, d = q.shape
    bs_blk = k_pool.shape[1]
    mb = block_table.shape[1]
    L = mb * bs_blk
    ck = k_pool[block_table]
    cv = v_pool[block_table]
    if k_scale is not None:
        ck = dequantize_kv(ck, k_scale[block_table])
        cv = dequantize_kv(cv, v_scale[block_table])
    ck = ck.reshape((b, L) + ck.shape[3:])
    cv = cv.reshape((b, L) + cv.shape[3:])
    logits = jnp.einsum("bqnd,bknd->bnqk", q, ck,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    visible = (jnp.arange(L)[None, None, :]
               <= pos[:, :, None])                   # [B, s, L]
    logits = jnp.where(visible[:, None, :, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(cv.dtype)
    ctx = jnp.einsum("bnqk,bknd->bqnd", probs, cv)
    # int8 path dequantized to f32; hand back the query's dtype so the
    # output contract matches the float pools'
    return ctx if k_scale is None else ctx.astype(q.dtype)


#: table width at or below which the blockwise loop uses a STATIC
#: trip count (visit every table slot, masked): XLA compiles a
#: known-trip-count loop markedly faster than a dynamic-bound while,
#: and at <= 8 blocks the masked extra iterations cost about what the
#: bound bookkeeping would. Wider tables — where per-step work
#: tracking LIVE blocks instead of table width is the whole point —
#: take the traced bound. Trace-time dispatch: outputs are identical
#: either way (a masked iteration is an exact no-op).
_STATIC_TRIP_MAX_BLOCKS = 8


def _blockwise(q, k_pool, v_pool, block_table, pos, scale,
               k_scale=None, v_scale=None):
    """Online-softmax over each row's live blocks, pure ``lax``: the
    CPU tier-1 formulation of the fused kernel (and the fallback for
    any non-TPU backend). ONE ``fori_loop`` — iteration ``j`` gathers
    block ``j`` of every row at once ([B, bs, N, D], a
    live-block-sized transient) and folds it into the recurrence;
    rows whose own depth is < j mask to -inf, which makes their
    update an EXACT no-op (p = 0, correction = 1). The trip count is
    the batch's deepest live block count (traced), so mixed-depth
    batches cost the deepest row, never the table width — except on
    narrow tables (see :data:`_STATIC_TRIP_MAX_BLOCKS`), where a
    static count compiles faster and costs the same."""
    b, s, n, d = q.shape
    bs_blk = k_pool.shape[1]
    mb = block_table.shape[1]
    nblk = _nblocks(pos, bs_blk, mb)             # [B]

    m0 = jnp.full((b, s, n), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, s, n), jnp.float32)
    a0 = jnp.zeros((b, s, n, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        # clamp keeps the gather in-bounds for frozen rows; the
        # (j < nblk) mask below is what actually freezes them
        jj = jnp.minimum(j, nblk - 1)            # [B]
        bid = jnp.take_along_axis(block_table, jj[:, None],
                                  axis=1)[:, 0]  # [B]
        kb = k_pool[bid]                         # [B, bs, N, D]
        vb = v_pool[bid]
        if k_scale is not None:
            # int8 fast path: the gather above moved the int8 bytes;
            # dequant happens here, on the one-block transient
            kb = dequantize_kv(kb, k_scale[bid])
            vb = dequantize_kv(vb, v_scale[bid])
        sc = jnp.einsum("bqnd,btnd->bqnt", q, kb,
                        preferred_element_type=jnp.float32)
        sc = sc * scale                          # [B, s, N, bs]
        kpos = jj[:, None] * bs_blk + jnp.arange(bs_blk)[None, :]
        vis = (kpos[:, None, :] <= pos[:, :, None]) \
            & (j < nblk)[:, None, None]          # [B, s, bs]
        sc = jnp.where(vis[:, :, None, :], sc, -jnp.inf)
        m_blk = jnp.max(sc, axis=-1)             # [B, s, N]
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(jnp.isneginf(sc), 0.0,
                      jnp.exp(sc - safe_m[..., None]))
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bqnt,btnd->bqnd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    trips = mb if mb <= _STATIC_TRIP_MAX_BLOCKS else jnp.max(nblk)
    m, l, acc = jax.lax.fori_loop(0, trips, body, (m0, l0, a0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(q.dtype)


def _paged_kernel(*refs, scale, block_size, num_heads, quantized):
    """One (batch*head, block_j) program: fold this block into the
    online-softmax accumulators; emit on the last table slot. The K/V
    BlockSpec index maps already routed the RIGHT pool block here (and
    clamped dead slots to the last live block, skipping their copy), so
    the kernel only guards compute. ``quantized`` adds per-head scale
    refs riding the SAME index maps as K/V; dequant happens in-VMEM
    right after the (int8-sized) copy — the bandwidth the fast path
    saves is exactly the bytes the DMA no longer moves."""
    from jax.experimental import pallas as pl

    if quantized:
        (table_ref, nblk_ref, q_ref, pos_ref, k_ref, v_ref, ks_ref,
         vs_ref, o_ref, acc_ref, m_ref, l_ref) = refs
    else:
        (table_ref, nblk_ref, q_ref, pos_ref, k_ref, v_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
        ks_ref = vs_ref = None
    bn = pl.program_id(0)
    j = pl.program_id(1)
    b = bn // num_heads
    nblk = nblk_ref[b]
    s_q = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j < nblk)
    def _accumulate():
        q = q_ref[0, :, 0, :].astype(jnp.float32)       # [s_q, D]
        kb = k_ref[0, :, 0, :].astype(jnp.float32)      # [bs, D]
        vb = v_ref[0, :, 0, :].astype(jnp.float32)
        if ks_ref is not None:
            kb = kb * ks_ref[0, :, 0][:, None]
            vb = vb * vs_ref[0, :, 0][:, None]
        sc = jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [s_q, bs]
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (s_q, block_size), 1)
        vis = kpos <= pos_ref[0][:, None]
        sc = jnp.where(vis, sc, -jnp.inf)
        m = m_ref[0]
        l = l_ref[0]
        m_blk = jnp.max(sc, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(jnp.isneginf(sc), 0.0,
                      jnp.exp(sc - safe_m[:, None]))
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        m_ref[0] = m_new
        l_ref[0] = l * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, vb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        l = l_ref[0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0, :] = (acc_ref[...] / l_safe[:, None]) \
            .astype(o_ref.dtype)


def _pallas(q, k_pool, v_pool, block_table, pos, scale, interpret,
            k_scale=None, v_scale=None):
    """The TPU kernel: block table as scalar prefetch, K/V index maps
    read it, dead slots clamp to the last live block (copy skipped).
    int8 pools bring their ``[P, bs, N]`` scales along on the same
    index maps; the kernel dequantizes in VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, n, d = q.shape
    bs_blk = k_pool.shape[1]
    mb = block_table.shape[1]
    table = block_table.astype(jnp.int32)
    nblk = _nblocks(pos.astype(jnp.int32), bs_blk, mb)      # [B]
    quantized = k_scale is not None

    def kv_index(bn, j, table_ref, nblk_ref):
        row = bn // n
        live = jnp.minimum(j, nblk_ref[row] - 1)
        return (table_ref[row, live], 0, bn % n, 0)

    def scale_index(bn, j, table_ref, nblk_ref):
        # the scales ride the exact pool-block routing K/V use (same
        # dead-slot clamp, so their copy is skipped together)
        row = bn // n
        live = jnp.minimum(j, nblk_ref[row] - 1)
        return (table_ref[row, live], 0, bn % n)

    in_specs = [
        pl.BlockSpec((1, s_q, 1, d),
                     lambda bn, j, t, nb: (bn // n, 0, bn % n, 0)),
        pl.BlockSpec((1, s_q),
                     lambda bn, j, t, nb: (bn // n, 0)),
        pl.BlockSpec((1, bs_blk, 1, d), kv_index),
        pl.BlockSpec((1, bs_blk, 1, d), kv_index),
    ]
    inputs = [table, nblk, q, pos.astype(jnp.int32), k_pool, v_pool]
    if quantized:
        in_specs.append(pl.BlockSpec((1, bs_blk, 1), scale_index))
        in_specs.append(pl.BlockSpec((1, bs_blk, 1), scale_index))
        inputs.append(k_scale.astype(jnp.float32))
        inputs.append(v_scale.astype(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * n, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, s_q, 1, d), lambda bn, j, t, nb: (bn // n, 0, bn % n, 0)),
        scratch_shapes=[
            pltpu.VMEM((s_q, d), jnp.float32),   # acc
            pltpu.VMEM((1, s_q), jnp.float32),   # running max
            pltpu.VMEM((1, s_q), jnp.float32),   # running denominator
        ],
    )
    kernel = functools.partial(_paged_kernel, scale=scale,
                               block_size=bs_blk, num_heads=n,
                               quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*inputs)


def paged_attention(q, k_pool, v_pool, block_table, pos, scale=None,
                    impl=None, interpret=None, force_pallas=False,
                    k_scale=None, v_scale=None):
    """Attend ``q`` against paged K/V through ``block_table``.

    ``pos [B, S_q]`` is each query's logical position (it sees key
    positions ``<= pos``; the caller wrote this call's K/V through the
    table already). ``impl``: None/"auto" picks the Pallas kernel on
    TPU backends and the blockwise ``lax`` formulation elsewhere
    (same allowlist policy as :func:`ops.flash_attention`);
    "gather" is PR 8's materialize-the-view reference oracle;
    "blockwise"/"pallas" force a specific fused formulation
    (``interpret``/``force_pallas`` route the kernel through the
    Pallas interpreter for CPU tests). ``k_scale``/``v_scale``
    (``[P, block_size, heads]`` float32, both or neither) mark the
    pools as int8 codes and dequantize them inside the chosen
    formulation — see the module docstring's int8-KV section."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    pos = jnp.asarray(pos, jnp.int32)
    block_table = jnp.asarray(block_table, jnp.int32)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    if impl in (None, "auto"):
        on_tpu = jax.default_backend() in ("tpu", "axon")
        impl = "pallas" if (on_tpu or force_pallas) else "blockwise"
    if impl == "gather":
        return _gather(q, k_pool, v_pool, block_table, pos, scale,
                       k_scale=k_scale, v_scale=v_scale)
    if impl == "blockwise":
        return _blockwise(q, k_pool, v_pool, block_table, pos, scale,
                          k_scale=k_scale, v_scale=v_scale)
    if impl == "pallas":
        if interpret is None:
            interpret = jax.default_backend() not in ("tpu", "axon")
        return _pallas(q, k_pool, v_pool, block_table, pos, scale,
                       interpret, k_scale=k_scale, v_scale=v_scale)
    raise ValueError(
        "unknown paged-attention impl {!r}; expected one of "
        "None/'auto', 'pallas', 'blockwise', 'gather'".format(impl))
