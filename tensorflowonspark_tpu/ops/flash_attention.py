"""Fused flash attention (Pallas TPU kernel) with XLA fallback.

Blocked online-softmax attention: Q tiles stream through VMEM while the
kernel loops over KV tiles, keeping the [S, S] score matrix out of HBM
entirely — the standard flash recurrence, laid out for the MXU (128-wide
tiles, bf16 matmuls with f32 accumulators/stats).

``flash_attention`` is differentiable via custom_vjp: the backward pass
recomputes attention in XLA from the saved inputs (rematerialization —
trades FLOPs for memory exactly like ``jax.checkpoint`` would; a fused
backward kernel is a later optimization).

Layout: [batch, seq, heads, head_dim], same contract as
``parallel.ring_attention`` (whose per-shard block update this kernel can
replace for ring+flash composition).
"""

import functools

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _reference(q, k, v, causal, scale):
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention)

    return reference_attention(q, k, v, causal=causal, scale=scale)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q, block_k,
            seq_len):
    """One (batch*head, q-block) program: loop KV tiles, online softmax."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    d = q.shape[-1]
    qi = pl.program_id(1)
    q_offset = qi * block_q

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kv = seq_len // block_k

    def body(kv_i, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kv_i * block_k, block_k), :]   # [BK, D]
        v_blk = v_ref[0, pl.ds(kv_i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BQ, BK]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kv_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # static full loop; causal masking zeroes future tiles (skipping them
    # needs a traced bound — a scheduling optimization for later)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m, l, acc))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, n, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (
        "seq len {} must be divisible by block sizes ({}, {})"
        .format(s, block_q, block_k))

    # [B, S, N, D] -> [B*N, S, D]: each program owns one (batch, head)
    def fold(x):
        return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * n, s, d))

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (b * n, s // block_q)
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * n, s, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.transpose(jnp.reshape(out, (b, n, s, d)), (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    # rematerialized backward through the XLA reference (correct + simple;
    # the flash recurrence's fused backward kernel is a later optimization)
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    force_pallas=False, interpret=None):
    """Fused attention. [B, S, N, D] in, [B, S, N, D] out.

    On TPU backends runs the Pallas kernel; elsewhere falls back to the
    XLA reference (``interpret=True`` forces the kernel through the
    Pallas interpreter — used by tests to validate kernel logic on CPU).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    # allowlist, not denylist: unknown plugin backends must take the XLA
    # fallback, not the TPU kernel ('axon' is the tunneled TPU platform)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if interpret is None:
        interpret = not on_tpu
    if not (on_tpu or force_pallas):
        return _reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
