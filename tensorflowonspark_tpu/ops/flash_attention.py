"""Fused flash attention (Pallas TPU kernels) with XLA fallback.

Blocked online-softmax attention: Q tiles stream through VMEM while the
kernel loops over KV tiles, keeping the [S, S] score matrix out of HBM
entirely — the standard flash recurrence, laid out for the MXU (128-wide
tiles, bf16 matmuls with f32 accumulators/stats).

Both directions are fused:

- forward: online-softmax kernel, also emitting the per-row logsumexp
  (LSE) needed by the backward.
- backward: two kernels in the FlashAttention-2 factorization —
  ``dq`` (grid over Q tiles, loops KV) and ``dk/dv`` (grid over KV
  tiles, loops Q) — recomputing P tiles from the saved LSE with f32
  accumulators, so training memory stays O(S) per (batch, head) instead
  of the O(S²) score matrix the rematerialized-XLA vjp used to build.
  ``delta = rowsum(dO ⊙ O)`` is precomputed in XLA (one fused
  elementwise+reduce).

Masking: causal (in-kernel position compare) and/or a per-key padding
mask (``key_mask`` [B, S_k] bool — BERT-style), carried through both
directions as an additive 0/-inf bias row.

Rectangular attention is supported (``S_q != S_k`` — cross attention);
causal requires equal lengths.

Non-TPU backends take the XLA reference for both directions (and the
Pallas interpreter validates the kernels on CPU in tests).

Layout: [batch, seq, heads, head_dim], same contract as
``parallel.ring_attention`` (whose per-shard block update this kernel
replaces in ``ring_flash_attention``).
"""

import functools

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _reference(q, k, v, causal, scale, bias=None):
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention)

    if bias is None:
        return reference_attention(q, k, v, causal=causal, scale=scale)
    out, _ = _reference_lse(q, k, v, causal, scale, bias)
    return out


def _reference_lse(q, k, v, causal, scale, bias=None):
    """XLA (out, lse [b, n, s_q]) pair — same contract as the kernels.

    ``bias``: optional [B, S_k] additive f32 row (0 / -inf key mask).
    """
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias[:, None, None, :]
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)   # [b, n, q]
    safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
    p = jnp.where(jnp.isneginf(logits), 0.0,
                  jnp.exp(logits - safe[..., None]))
    out = jnp.einsum("bnqk,bknd->bqnd", p.astype(v.dtype), v)
    return out.astype(q.dtype), lse


def _causal_mask(s, q_offset, k_offset, block_q, block_k):
    q_pos = q_offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, -jnp.inf)


def _fwd_kernel(*refs, scale, causal, block_q, block_k, seq_len, has_bias):
    """One (batch*head, q-block) program: loop KV tiles, online softmax."""
    from jax.experimental import pallas as pl

    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref), bias_ref = refs, None

    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    d = q.shape[-1]
    qi = pl.program_id(1)
    q_offset = qi * block_q

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kv = seq_len // block_k

    def body(kv_i, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kv_i * block_k, block_k), :]   # [BK, D]
        v_blk = v_ref[0, pl.ds(kv_i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BQ, BK]
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(kv_i * block_k, block_k)][None, :]
        if causal:
            s = _causal_mask(s, q_offset, kv_i * block_k, block_q, block_k)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # static full loop; causal masking zeroes future tiles (skipping them
    # needs a traced bound — a scheduling optimization for later)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m, l, acc))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # LSE = m + log(l): the only softmax statistic the backward needs
    lse_ref[0] = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))


def _dq_kernel(*refs, scale, causal, block_q, block_k, seq_len, has_bias):
    """dQ for one (batch*head, q-block): loop KV tiles, recompute P."""
    from jax.experimental import pallas as pl

    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref, dq_ref \
            = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref), \
            bias_ref = refs, None

    q = q_ref[0].astype(jnp.float32) * scale           # [BQ, D]
    do = do_ref[0].astype(jnp.float32)                 # [BQ, D]
    lse = lse_ref[0]                                   # [BQ]
    delta = delta_ref[0]                               # [BQ]
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
    qi = pl.program_id(1)
    q_offset = qi * block_q

    dq_acc = jnp.zeros_like(q)
    num_kv = seq_len // block_k

    def body(kv_i, dq_acc):
        k_blk = k_ref[0, pl.ds(kv_i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kv_i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(kv_i * block_k, block_k)][None, :]
        if causal:
            s = _causal_mask(s, q_offset, kv_i * block_k, block_q, block_k)
        p = jnp.where(jnp.isneginf(s), 0.0,
                      jnp.exp(s - lse_safe[:, None]))  # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot_general(
            ds, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, D]

    dq_acc = jax.lax.fori_loop(0, num_kv, body, dq_acc)
    dq_ref[0] = (dq_acc * scale).astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, block_q, block_k, seq_len, has_bias):
    """dK/dV for one (batch*head, kv-block): loop Q tiles, recompute P."""
    from jax.experimental import pallas as pl

    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref, \
            dk_ref, dv_ref = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
         dv_ref), bias_ref = refs, None

    k_blk = k_ref[0].astype(jnp.float32)               # [BK, D]
    v_blk = v_ref[0].astype(jnp.float32)
    d = k_blk.shape[-1]
    kv_i = pl.program_id(1)
    k_offset = kv_i * block_k
    bias_blk = bias_ref[0] if bias_ref is not None else None  # [BK]

    dk_acc = jnp.zeros((block_k, d), jnp.float32)
    dv_acc = jnp.zeros((block_k, d), jnp.float32)
    num_q = seq_len // block_q

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32) * scale               # [BQ, D] (scaled)
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q)]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q)]
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        s = jax.lax.dot_general(
            q_blk, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        if bias_blk is not None:
            s = s + bias_blk[None, :]
        if causal:
            s = _causal_mask(s, qi * block_q, k_offset, block_q, block_k)
        p = jnp.where(jnp.isneginf(s), 0.0,
                      jnp.exp(s - lse_safe[:, None]))
        dv_new = dv_acc + jax.lax.dot_general(
            p, do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BK, D]
        dp = jax.lax.dot_general(
            do_blk, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        ds = p * (dp - delta[:, None])
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BK, D] (has scale)
        return dk_new, dv_new

    dk_acc, dv_acc = jax.lax.fori_loop(0, num_q, body, (dk_acc, dv_acc))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _fold(x, b, s, n, d):
    """[B, S, N, D] -> [B*N, S, D]: each program owns one (batch, head)."""
    return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * n, s, d))


def _unfold(x, b, s, n, d):
    return jnp.transpose(jnp.reshape(x, (b, n, s, d)), (0, 2, 1, 3))


# NOTE: the bias row is per-BATCH ([B, S_k]); the grids run over
# bh = b*N + n, so bias BlockSpec index maps use bh // N (closing over
# the static head count) instead of materializing an N-fold repeat.


def _check_blocks(s_q, s_k, block_q, block_k):
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    assert s_q % block_q == 0 and s_k % block_k == 0, (
        "seq lens ({}, {}) must be divisible by block sizes ({}, {})"
        .format(s_q, s_k, block_q, block_k))
    return block_q, block_k


def _flash_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    """Returns (out [B,Sq,N,D], lse [B*N, Sq]). Sq may differ from the
    KV length (cross attention); causal requires Sq == Sk.
    ``bias``: optional [B, S_k] additive f32 row (key mask)."""
    from jax.experimental import pallas as pl

    b, s_q, n, d = q.shape
    s_k = k.shape[1]
    assert not causal or s_q == s_k, "causal needs equal q/kv lengths"
    block_q, block_k = _check_blocks(s_q, s_k, block_q, block_k)

    qf = _fold(q, b, s_q, n, d)
    kf = _fold(k, b, s_k, n, d)
    vf = _fold(v, b, s_k, n, d)
    grid = (b * n, s_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s_k, has_bias=bias is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, s_k, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, s_k, d), lambda bh, i: (bh, 0, 0)),
    ]
    inputs = [qf, kf, vf]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, s_k), lambda bh, i, n=n: (bh // n, 0)))
        inputs.append(bias.astype(jnp.float32))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * n, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * n, s_q), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return _unfold(out, b, s_q, n, d), lse


def _flash_bwd(q, k, v, bias, out, lse, g, causal, scale, block_q,
               block_k, interpret, g_lse=None):
    """Fused dq/dk/dv. All tensors [B,S,N,D] except lse [B*N,S].

    ``g_lse`` ([B*N, S] or None): cotangent of the lse output for the
    (out, lse) variant — enters as ds += p * g_lse, folded into delta.
    """
    from jax.experimental import pallas as pl

    b, s_q, n, d = q.shape
    s_k = k.shape[1]
    block_q, block_k = _check_blocks(s_q, s_k, block_q, block_k)

    qf = _fold(q, b, s_q, n, d)
    kf = _fold(k, b, s_k, n, d)
    vf = _fold(v, b, s_k, n, d)
    of = _fold(out, b, s_q, n, d)
    gf = _fold(g, b, s_q, n, d)
    bf = None if bias is None else bias.astype(jnp.float32)
    has_bias = bf is not None
    # delta = rowsum(dO ⊙ O): one fused XLA elementwise+reduce, f32
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)                            # [B*N, Sq]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)

    full = lambda bh, i: (bh, 0, 0)  # noqa: E731
    full_vec = lambda bh, i: (bh, 0)  # noqa: E731

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, s_k, d), full),
        pl.BlockSpec((1, s_k, d), full),
    ]
    dq_inputs = [qf, kf, vf]
    if has_bias:
        dq_specs.append(
            pl.BlockSpec((1, s_k), lambda bh, i, n=n: (bh // n, 0)))
        dq_inputs.append(bf)
    dq_specs += [
        pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
        pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
    ]
    dq_inputs += [gf, lse, delta]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s_k,
                          has_bias=has_bias),
        grid=(b * n, s_q // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * n, s_q, d), q.dtype),
        interpret=interpret,
    )(*dq_inputs)

    dkv_specs = [
        pl.BlockSpec((1, s_q, d), full),
        pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
    ]
    dkv_inputs = [qf, kf, vf]
    if has_bias:
        dkv_specs.append(
            pl.BlockSpec((1, block_k), lambda bh, i, n=n: (bh // n, i)))
        dkv_inputs.append(bf)
    dkv_specs += [
        pl.BlockSpec((1, s_q, d), full),
        pl.BlockSpec((1, s_q), full_vec),
        pl.BlockSpec((1, s_q), full_vec),
    ]
    dkv_inputs += [gf, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s_q,
                          has_bias=has_bias),
        grid=(b * n, s_k // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * n, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * n, s_k, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_inputs)

    return (_unfold(dq, b, s_q, n, d), _unfold(dk, b, s_k, n, d),
            _unfold(dv, b, s_k, n, d))


def _flash(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    """Output-only attention: _flash_pair with the lse discarded.

    Differentiation flows through _flash_pair's custom_vjp; the unused
    lse output contributes a zero cotangent (folded into delta at no
    meaningful cost), so no second custom_vjp is needed.
    """
    out, _ = _flash_pair(q, k, v, bias, causal, scale, block_q, block_k,
                         interpret)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_pair(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    """(out, lse) variant — the composable building block.

    Callers that merge attention partials (ring attention) need the
    per-row logsumexp alongside the normalized output, and need
    gradients to flow through BOTH: ``d lse / d s = p``, which folds
    into the existing backward kernels as ``delta_eff = delta - g_lse``
    (ds = p * (dp - delta + g_lse)) — no extra kernel.
    """
    return _flash_fwd(q, k, v, bias, causal, scale, block_q, block_k,
                      interpret)


def _flash_pair_vjp_fwd(q, k, v, bias, causal, scale, block_q, block_k,
                        interpret):
    out, lse = _flash_fwd(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret)
    return (out, lse), (q, k, v, bias, out, lse)


def _flash_pair_vjp_bwd(causal, scale, block_q, block_k, interpret,
                        residuals, gs):
    q, k, v, bias, out, lse = residuals
    g, g_lse = gs
    dq, dk, dv = _flash_bwd(q, k, v, bias, out, lse, g, causal, scale,
                            block_q, block_k, interpret, g_lse=g_lse)
    return dq, dk, dv, None


_flash_pair.defvjp(_flash_pair_vjp_fwd, _flash_pair_vjp_bwd)


def _mask_to_bias(key_mask):
    """[B, S_k] bool -> [B, S_k] f32 additive row (True = attend)."""
    if key_mask is None:
        return None
    return jnp.where(key_mask, 0.0, -jnp.inf).astype(jnp.float32)


def flash_attention_lse(q, k, v, causal=False, scale=None, key_mask=None,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                        force_pallas=False, interpret=None):
    """Fused attention returning ``(out [B,S,N,D], lse [B,N,S])``.

    The building block for partial-attention composition (ring
    attention's per-step block update): two partials (out_a, lse_a),
    (out_b, lse_b) over disjoint KV merge exactly as

        lse = logaddexp(lse_a, lse_b)
        out = out_a * exp(lse_a - lse) + out_b * exp(lse_b - lse)

    Differentiable in q/k/v including through the lse output. Rows that
    attend to nothing (fully-masked) have lse == -inf and out == 0.

    ``key_mask``: optional [B, S_k] bool, True = key is attendable (the
    BERT-style padding mask).

    Backend policy matches :func:`flash_attention`: Pallas kernels on
    TPU; the XLA reference pair elsewhere (``interpret=True`` /
    ``force_pallas`` route through the Pallas interpreter for tests).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    bias = _mask_to_bias(key_mask)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if not (on_tpu or force_pallas or interpret):
        return _reference_lse(q, k, v, causal, scale, bias)
    if interpret is None:
        interpret = not on_tpu
    b, s, n, d = q.shape
    out, lse = _flash_pair(q, k, v, bias, causal, scale, block_q, block_k,
                           interpret)
    return out, jnp.reshape(lse, (b, n, s))


def flash_attention(q, k, v, causal=False, scale=None, key_mask=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    force_pallas=False, interpret=None):
    """Fused attention. [B, S, N, D] in, [B, S, N, D] out.

    ``key_mask``: optional [B, S_k] bool, True = key is attendable.
    On TPU backends runs the Pallas kernels (both directions); elsewhere
    falls back to the XLA reference (``interpret=True`` forces the
    kernels through the Pallas interpreter — used by tests to validate
    kernel logic on CPU).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    bias = _mask_to_bias(key_mask)
    # allowlist, not denylist: unknown plugin backends must take the XLA
    # fallback, not the TPU kernel ('axon' is the tunneled TPU platform)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if interpret is None:
        interpret = not on_tpu
    if not (on_tpu or force_pallas):
        return _reference(q, k, v, causal, scale, bias)
    return _flash(q, k, v, bias, causal, scale, block_q, block_k,
                  interpret)
