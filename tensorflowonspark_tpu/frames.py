"""Columnar feed frames: the wire format of the data plane.

The reference ships feed data as pickled lists of per-record tuples
through a multiprocessing manager proxy (``TFSparkNode._train`` →
``TFManager`` queues; SURVEY.md §3.2 hot path) — every byte is pickled,
TCP-framed, and unpickled per hop, and the consumer re-stacks records one
by one. On a host whose CPU budget is shared with the accelerator runtime
(the common TPU-VM shape), those copies ARE the feed ceiling.

Here the feeder stacks records into contiguous per-column arrays once,
and the frame codec moves them as raw bytes:

- :class:`ColumnarChunk` — a batch of N records as column arrays
  (positional or named), sliceable without touching the data.
- :func:`encode` — object → list of buffers ``[u32 hdrlen][hdr pickle]
  [col bytes]...``; column payloads are raw array memory, never pickled.
  Arbitrary objects (markers, legacy record lists) embed in the header.
- :func:`encode_multi` — several objects → ONE frame (one transport
  message). The feeder coalesces tiny chunks and trailing markers this
  way so per-message fixed costs (header pickle, ring wakeup, slot
  bookkeeping) amortize across them — the small-batch regime pays those
  costs per chunk where the bulk regime amortizes them per 38MB frame.
- :func:`decode` — memoryview → object; column arrays come back as
  ZERO-COPY views into the source buffer (callers that outlive the
  buffer must ``.materialize()``). Multi-object frames decode to a
  :class:`FrameList` (so a frame carrying a pickled *record list* stays
  distinguishable from a frame carrying several objects).

Used by the shm ring transport (shm.py) where the buffers land in the
mmap with a single gather-memcpy; the manager-queue transport pickles
:class:`ColumnarChunk` whole (protocol 5 moves the column arrays as
single out-of-band buffers, so even that path stacks exactly once).
"""

import pickle
import struct

import numpy as np

_LEN = struct.Struct("<I")


class FrameList(list):
    """``decode()`` result for a multi-object frame (``encode_multi``).

    A plain ``list`` would be ambiguous: legacy record-list chunks also
    travel as one pickled list inside an object frame, and consumers
    (DataFeed) treat those as a single segment of records. The subclass
    marks "these are SEPARATE feed items sharing one transport message".
    """

    __slots__ = ()


class ColumnarChunk(object):
    """N records stacked column-wise.

    ``cols``: list of arrays, each with leading dim N (record index).
    ``names``: optional tuple of field names (dict-shaped records);
    positional (tuple-shaped records) when None.
    """

    __slots__ = ("cols", "names", "scalar")

    def __init__(self, cols, names=None, scalar=False):
        self.cols = list(cols)
        self.names = tuple(names) if names is not None else None
        self.scalar = scalar  # records were bare values, not tuples/dicts

    def __len__(self):
        return 0 if not self.cols else int(self.cols[0].shape[0])

    def slice(self, start, stop):
        """View of records [start:stop) — no data movement."""
        return ColumnarChunk([c[start:stop] for c in self.cols], self.names,
                             self.scalar)

    def materialize(self):
        """Own the memory (copy out of any transient buffer).

        Must COPY views: ``np.ascontiguousarray`` returns an already-
        contiguous view unchanged, which for ring-backed ``frombuffer``
        views would alias memory the producer is about to overwrite —
        silent data corruption. OWNDATA is the contract.
        """
        self.cols = [c if c.flags["OWNDATA"] and c.flags["C_CONTIGUOUS"]
                     else np.array(c, order="C", copy=True)
                     for c in self.cols]
        return self

    def record(self, i):
        """Record ``i`` in the original row shape (value, tuple, or dict)."""
        if self.scalar:
            return self.cols[0][i]
        vals = [c[i] for c in self.cols]
        if self.names is None:
            return tuple(vals)
        return dict(zip(self.names, vals))

    def records(self):
        """Back to row-major records (compat path, copies)."""
        return [self.record(i) for i in range(len(self))]

    @classmethod
    def from_records(cls, records, names=None):
        """Stack row records (bare values, tuples, or dicts) into columns.

        Raises TypeError/ValueError for ragged or non-array-able records —
        callers fall back to the object frame.
        """
        if not records:
            return cls([], names)
        first = records[0]
        if isinstance(first, dict):
            names = tuple(first.keys()) if names is None else tuple(names)
            cols = [np.stack([np.asarray(r[k]) for r in records])
                    for k in names]
            return cls(cols, names)
        if isinstance(first, (tuple, list)):
            width = len(first)
            cols = [np.stack([np.asarray(r[i]) for r in records])
                    for i in range(width)]
            return cls(cols, None)
        return cls([np.stack([np.asarray(r) for r in records])], None,
                   scalar=True)


def concat(chunks):
    """Concatenate ColumnarChunks (one copy; used for batch re-slicing)."""
    nonempty = [c for c in chunks if len(c)]
    if not nonempty:
        # All-empty input: preserve the shape metadata of the first chunk
        # so downstream column lookups still resolve.
        first = chunks[0]
        return ColumnarChunk(first.cols, first.names, first.scalar)
    chunks = nonempty
    if len(chunks) == 1:
        return chunks[0]
    names = chunks[0].names
    width = len(chunks[0].cols)
    cols = [np.concatenate([c.cols[i] for c in chunks]) for i in range(width)]
    return ColumnarChunk(cols, names)


def _part_meta(obj, payloads):
    """Header entry for one object; column payload buffers append to
    ``payloads``."""
    if isinstance(obj, ColumnarChunk):
        cols = [np.ascontiguousarray(c) for c in obj.cols]
        payloads.extend(memoryview(c).cast("B") for c in cols)
        return {"k": "cols", "names": obj.names, "scalar": obj.scalar,
                "meta": [(c.dtype.str, c.shape) for c in cols]}
    return {"k": "obj", "obj": obj}


def _decode_part(hdr, view, off):
    """One header entry → (object, next payload offset). Column arrays are
    zero-copy views into ``view``."""
    if hdr["k"] == "obj":
        return hdr["obj"], off
    cols = []
    for dtype_str, shape in hdr["meta"]:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        arr = np.frombuffer(view, dtype=dt, count=n, offset=off)
        cols.append(arr.reshape(shape))
        off += n * dt.itemsize
    return ColumnarChunk(cols, hdr["names"], hdr.get("scalar", False)), off


def encode(obj):
    """object → list of byte-like buffers forming one frame."""
    payloads = []
    hdr = pickle.dumps(_part_meta(obj, payloads), protocol=5)
    return [_LEN.pack(len(hdr)), hdr] + payloads


def encode_multi(objs):
    """Several objects → ONE frame (one transport message).

    Column payloads of every ColumnarChunk ride as raw bytes after a
    single pickled header describing all parts, so N tiny objects cost
    one message's fixed overhead instead of N. ``decode`` returns them
    as a :class:`FrameList` in order.
    """
    payloads = []
    parts = [_part_meta(obj, payloads) for obj in objs]
    hdr = pickle.dumps({"k": "multi", "parts": parts}, protocol=5)
    return [_LEN.pack(len(hdr)), hdr] + payloads


def frame_bytes(buffers):
    """Total wire bytes of an :func:`encode`/:func:`encode_multi`
    result — the PHYSICAL transfer cost (header + raw column payloads
    as they sit in memory). This is the one place ship-byte accounting
    reads (PR 17): int8 KV shipments are priced by their codes+scales
    buffers, never by the logical dequantized size."""
    total = 0
    for b in buffers:
        total += memoryview(b).nbytes
    return total


def decode(view):
    """One frame (memoryview/bytes) → object (or FrameList for multi).

    ColumnarChunk columns are zero-copy views into ``view``.
    """
    view = memoryview(view)
    (hdrlen,) = _LEN.unpack_from(view, 0)
    hdr = pickle.loads(view[4:4 + hdrlen])
    off = 4 + hdrlen
    if hdr["k"] == "multi":
        out = FrameList()
        for part in hdr["parts"]:
            obj, off = _decode_part(part, view, off)
            out.append(obj)
        return out
    obj, _ = _decode_part(hdr, view, off)
    return obj
