"""Columnar feed frames: the wire format of the data plane.

The reference ships feed data as pickled lists of per-record tuples
through a multiprocessing manager proxy (``TFSparkNode._train`` →
``TFManager`` queues; SURVEY.md §3.2 hot path) — every byte is pickled,
TCP-framed, and unpickled per hop, and the consumer re-stacks records one
by one. On a host whose CPU budget is shared with the accelerator runtime
(the common TPU-VM shape), those copies ARE the feed ceiling.

Here the feeder stacks records into contiguous per-column arrays once,
and the frame codec moves them as raw bytes:

- :class:`ColumnarChunk` — a batch of N records as column arrays
  (positional or named), sliceable without touching the data.
- :func:`encode` — object → list of buffers ``[u32 hdrlen][hdr pickle]
  [col bytes]...``; column payloads are raw array memory, never pickled.
  Arbitrary objects (markers, legacy record lists) embed in the header.
- :func:`decode` — memoryview → object; column arrays come back as
  ZERO-COPY views into the source buffer (callers that outlive the
  buffer must ``.materialize()``).

Used by the shm ring transport (shm.py) where the buffers land in the
mmap with a single gather-memcpy; the manager-queue transport pickles
:class:`ColumnarChunk` whole (protocol 5 moves the column arrays as
single out-of-band buffers, so even that path stacks exactly once).
"""

import pickle
import struct

import numpy as np

_LEN = struct.Struct("<I")


class ColumnarChunk(object):
    """N records stacked column-wise.

    ``cols``: list of arrays, each with leading dim N (record index).
    ``names``: optional tuple of field names (dict-shaped records);
    positional (tuple-shaped records) when None.
    """

    __slots__ = ("cols", "names", "scalar")

    def __init__(self, cols, names=None, scalar=False):
        self.cols = list(cols)
        self.names = tuple(names) if names is not None else None
        self.scalar = scalar  # records were bare values, not tuples/dicts

    def __len__(self):
        return 0 if not self.cols else int(self.cols[0].shape[0])

    def slice(self, start, stop):
        """View of records [start:stop) — no data movement."""
        return ColumnarChunk([c[start:stop] for c in self.cols], self.names,
                             self.scalar)

    def materialize(self):
        """Own the memory (copy out of any transient buffer).

        Must COPY views: ``np.ascontiguousarray`` returns an already-
        contiguous view unchanged, which for ring-backed ``frombuffer``
        views would alias memory the producer is about to overwrite —
        silent data corruption. OWNDATA is the contract.
        """
        self.cols = [c if c.flags["OWNDATA"] and c.flags["C_CONTIGUOUS"]
                     else np.array(c, order="C", copy=True)
                     for c in self.cols]
        return self

    def record(self, i):
        """Record ``i`` in the original row shape (value, tuple, or dict)."""
        if self.scalar:
            return self.cols[0][i]
        vals = [c[i] for c in self.cols]
        if self.names is None:
            return tuple(vals)
        return dict(zip(self.names, vals))

    def records(self):
        """Back to row-major records (compat path, copies)."""
        return [self.record(i) for i in range(len(self))]

    @classmethod
    def from_records(cls, records, names=None):
        """Stack row records (bare values, tuples, or dicts) into columns.

        Raises TypeError/ValueError for ragged or non-array-able records —
        callers fall back to the object frame.
        """
        if not records:
            return cls([], names)
        first = records[0]
        if isinstance(first, dict):
            names = tuple(first.keys()) if names is None else tuple(names)
            cols = [np.stack([np.asarray(r[k]) for r in records])
                    for k in names]
            return cls(cols, names)
        if isinstance(first, (tuple, list)):
            width = len(first)
            cols = [np.stack([np.asarray(r[i]) for r in records])
                    for i in range(width)]
            return cls(cols, None)
        return cls([np.stack([np.asarray(r) for r in records])], None,
                   scalar=True)


def concat(chunks):
    """Concatenate ColumnarChunks (one copy; used for batch re-slicing)."""
    nonempty = [c for c in chunks if len(c)]
    if not nonempty:
        # All-empty input: preserve the shape metadata of the first chunk
        # so downstream column lookups still resolve.
        first = chunks[0]
        return ColumnarChunk(first.cols, first.names, first.scalar)
    chunks = nonempty
    if len(chunks) == 1:
        return chunks[0]
    names = chunks[0].names
    width = len(chunks[0].cols)
    cols = [np.concatenate([c.cols[i] for c in chunks]) for i in range(width)]
    return ColumnarChunk(cols, names)


def encode(obj):
    """object → list of byte-like buffers forming one frame."""
    if isinstance(obj, ColumnarChunk):
        cols = [np.ascontiguousarray(c) for c in obj.cols]
        hdr = pickle.dumps({
            "k": "cols",
            "names": obj.names,
            "scalar": obj.scalar,
            "meta": [(c.dtype.str, c.shape) for c in cols],
        }, protocol=5)
        return [_LEN.pack(len(hdr)), hdr] + [memoryview(c).cast("B")
                                             for c in cols]
    hdr = pickle.dumps({"k": "obj", "obj": obj}, protocol=5)
    return [_LEN.pack(len(hdr)), hdr]


def decode(view):
    """One frame (memoryview/bytes) → object.

    ColumnarChunk columns are zero-copy views into ``view``.
    """
    view = memoryview(view)
    (hdrlen,) = _LEN.unpack_from(view, 0)
    hdr = pickle.loads(view[4:4 + hdrlen])
    if hdr["k"] == "obj":
        return hdr["obj"]
    off = 4 + hdrlen
    cols = []
    for dtype_str, shape in hdr["meta"]:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        arr = np.frombuffer(view, dtype=dt, count=n, offset=off)
        cols.append(arr.reshape(shape))
        off += n * dt.itemsize
    return ColumnarChunk(cols, hdr["names"], hdr.get("scalar", False))
