"""Supervision plane: failure detection, classification, and recovery.

The reference has no answer to a mid-job trainer or executor death
beyond Spark's coarse task retry (SURVEY.md §5): a killed trainer
strands the reservation barrier and the whole job reruns from scratch.
This module is the missing subsystem — it AGGREGATES liveness from three
signals the framework already produces, CLASSIFIES the failure, and
DRIVES a pluggable recovery policy end to end:

Signals (all ride the per-executor heartbeat lease that node.py's beat
thread publishes through the existing reservation ``Server``):

1. the lease itself — a missing/expired lease is executor loss (the
   whole bootstrap process died or went dark);
2. DataFeed progress counters (``feed_hb`` batches-served, via the
   broker kv) — a frozen counter with a live trainer is a feed-plane
   stall (queue transport) or ring wedge (shm transport);
3. trainer-process exit status surfaced by node.py's watchdog — an
   abnormal exit code (OOM SIGKILL's ``-9``) is a trainer crash.

Failure taxonomy: ``trainer_crash`` | ``feeder_stall`` | ``ring_wedge``
| ``executor_lost`` (plus ``engine_dead`` for watched serving engines
and synthesized kinds for failures that surface as task errors before a
beat can attribute them). docs/fault_tolerance.md has the policy matrix.

Recovery policies:

- :class:`RestartFromCheckpoint` — bounded retries with exponential
  backoff: tear the attempt down, resubmit the job via ``cluster.run``,
  let the map_fun restore the latest step through
  ``checkpoint.Checkpointer`` (the proven resubmit+restore story from
  tests/test_resume.py), and replay only the feed partitions no trainer
  acknowledged as consumed.
- :class:`Blacklist` — additionally exclude an executor that failed
  ``max_failures`` times and reform the cluster at reduced width (the
  built-in engine's job scheduler honors the exclusion).
- :class:`ElasticResize` — width as a recoverable dimension: on
  executor loss, reform IMMEDIATELY at width-1 (no blacklist
  permanence, no waiting for a replacement) with un-ACKed feed
  partitions rebalanced across the surviving width; a regrow probe
  watches engine capacity and reforms back up at the next checkpoint
  boundary (cooperative :class:`ResizeDrain` at the step site).
  Cross-mesh checkpoint restore (``checkpoint.respec_like`` +
  ``parallel.mesh.respec_for_width``) is what makes the width change
  transparent to sharded state.
- :class:`FailJob` — clean teardown, error re-raised on the driver
  (exactly today's unsupervised behavior, made explicit).
- :class:`RestartEngine` — the SERVING-plane policy (PR 4): a watched
  ``DecodeEngine`` whose scheduler died is rebuilt from its own
  construction config with bounded backoff and re-armed on the
  ``ModelServer``, instead of 503-ing forever.

Entry point: ``cluster.run(..., supervise=SupervisorConfig(...))``
returns a :class:`SupervisedCluster` with the familiar
``train``/``shutdown`` surface. The serving plane hooks in through
:meth:`Supervisor.watch`, which marks a ``ModelServer`` unhealthy (503
on ``/healthz``) the moment its ``DecodeEngine`` scheduler thread dies
— and, given ``restart=RestartEngine(...)``, auto-restarts the engine.

Replay granularity and the delivery guarantee, stated precisely:
partitions are acknowledged when the node *consumed* them (feeder join
succeeded) — NOT when a checkpoint covering them committed. Replay
never double-feeds an acked partition, so records consumed after the
last committed checkpoint are lost with the crashed trainer's state
(at-most-once over that window), while unacked partitions replay in
full. Recovery is therefore exactly-once precisely when every consumed
partition's checkpoint committed before the crash — the aligned
one-partition-per-checkpointed-step shape ``bench.py recovery`` and
tests/test_recovery.py pin, where the consume→commit window is the gap
between a partition's final ``next_batch`` and that step's
``ckpt.save`` returning. A map_fun that checkpoints coarser (or an
uncontrolled crash landing inside that window) under-counts rather
than double-counts; both modes remain strictly tighter than the
reference's whole-job rerun, but choose checkpoint cadence knowing
which side of the boundary you are on.
"""

import contextlib
import logging
import threading
import time

from tensorflowonspark_tpu import goodput as goodput_mod
from tensorflowonspark_tpu import tracing

logger = logging.getLogger(__name__)

#: classification kinds the monitor emits from lease evidence
KINDS = ("trainer_crash", "feeder_stall", "ring_wedge", "executor_lost")


class FailureEvent(object):
    """One classified failure: what died, where, and the evidence.

    ``payload`` is the classifying heartbeat lease's payload (plus
    whatever the reporter attached); :meth:`as_dict` surfaces the two
    observability exhibits every incident should travel with —
    the failing executor's beat-carried metrics snapshot (its
    feed-stage breakdown at the moment of classification: a
    ``feeder_stall`` arrives with the stalled executor's stages
    attached) and the flight recorder's recent tail (the black-box
    timeline of what the process was doing; see
    ``tracing.FlightRecorder``)."""

    __slots__ = ("kind", "executor_id", "detail", "payload", "t", "wall")

    def __init__(self, kind, executor_id=None, detail="", payload=None):
        self.kind = kind
        self.executor_id = executor_id
        self.detail = detail
        self.payload = payload or {}
        self.t = time.monotonic()
        self.wall = time.time()

    def as_dict(self):
        return {"kind": self.kind, "executor_id": self.executor_id,
                "detail": self.detail, "wall": self.wall,
                "evidence": {"metrics": self.payload.get("metrics"),
                             "flight": self.payload.get("flight")}}

    def __str__(self):
        where = "" if self.executor_id is None \
            else " on executor {}".format(self.executor_id)
        return "{}{}: {}".format(self.kind, where, self.detail)


class Decision(object):
    """A policy's verdict on one failure.

    ``RESIZE`` (elastic resize): reform at ``width`` — no blacklist
    permanence, no waiting for a replacement executor; the
    SupervisedCluster rebalances un-ACKed feed partitions across the
    new width through the existing per-partition ACK ledger."""

    __slots__ = ("action", "delay", "exclude", "reason", "width")

    FAIL = "fail"
    RESTART = "restart"
    RESIZE = "resize"

    def __init__(self, action, delay=0.0, exclude=frozenset(), reason="",
                 width=None):
        self.action = action
        self.delay = float(delay)
        self.exclude = frozenset(exclude)
        self.reason = reason
        self.width = None if width is None else int(width)


class FailJob(object):
    """Clean teardown; the error re-raises on the driver (the
    unsupervised default, made explicit and composable)."""

    def decide(self, event, restarts, failure_counts, excluded,
               num_executors, width=None):
        return Decision(Decision.FAIL,
                        reason="FailJob policy: no recovery attempted")


class RestartFromCheckpoint(object):
    """Resubmit-and-restore with bounded exponential backoff.

    ``max_restarts`` bounds recovery attempts across the job (not per
    executor); backoff grows ``backoff * backoff_factor**restarts``
    capped at ``max_backoff``. The restore itself happens trainer-side:
    a supervised map_fun opens its ``checkpoint.Checkpointer`` and
    restores the latest step (``fallback=True`` recommended — a writer
    killed mid-commit can leave a corrupt latest), exactly the
    resubmit+restore contract tests/test_resume.py proves.
    """

    def __init__(self, max_restarts=2, backoff=1.0, backoff_factor=2.0,
                 max_backoff=60.0):
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff = float(max_backoff)

    def decide(self, event, restarts, failure_counts, excluded,
               num_executors, width=None):
        if restarts >= self.max_restarts:
            return Decision(
                Decision.FAIL,
                reason="gave up after {} restart(s)".format(restarts))
        delay = min(self.backoff * self.backoff_factor ** restarts,
                    self.max_backoff)
        return Decision(Decision.RESTART, delay=delay,
                        reason="restart {} of {}".format(
                            restarts + 1, self.max_restarts))


class Blacklist(RestartFromCheckpoint):
    """RestartFromCheckpoint that additionally excludes a repeatedly
    failing executor and reforms the cluster at reduced width.

    ``max_failures``: attributed failures before an executor is
    blacklisted. ``min_width``: floor on the reformed cluster's size —
    dropping below it fails the job (a 1-node "cluster" may be exactly
    what you want for drain-and-finish, or not; choose explicitly).
    """

    def __init__(self, max_failures=2, min_width=1, max_restarts=4, **kw):
        super(Blacklist, self).__init__(max_restarts=max_restarts, **kw)
        self.max_failures = int(max_failures)
        self.min_width = int(min_width)

    def decide(self, event, restarts, failure_counts, excluded,
               num_executors, width=None):
        base = super(Blacklist, self).decide(
            event, restarts, failure_counts, excluded, num_executors)
        if base.action == Decision.FAIL:
            return base
        newly = {eid for eid, n in failure_counts.items()
                 if eid is not None and n >= self.max_failures} \
            - set(excluded)
        width_after = num_executors - len(set(excluded) | newly)
        if newly and width_after < self.min_width:
            return Decision(
                Decision.FAIL,
                reason="blacklisting {} would shrink the cluster below "
                       "min_width={}".format(sorted(newly), self.min_width))
        reason = base.reason
        if newly:
            reason += "; blacklisting executor(s) {} -> width {}".format(
                sorted(newly), width_after)
        return Decision(Decision.RESTART, delay=base.delay, exclude=newly,
                        reason=reason)


class ElasticResize(RestartFromCheckpoint):
    """Width as a RECOVERABLE dimension: on executor loss, reform
    immediately at width-1 instead of blacklisting (no permanence) or
    waiting for a replacement; when capacity returns, a regrow probe
    reforms back up at the next checkpoint boundary.

    Mechanics (docs/fault_tolerance.md "Elastic resize"):

    - ``executor_lost`` / ``reform_failed`` → ``Decision.RESIZE`` at
      the current width minus one (floored at ``min_width``; below it
      the job fails honestly). Un-ACKed feed partitions rebalance
      across the surviving width through the existing per-partition
      ACK ledger — nothing is lost, nothing double-fed.
    - ``shrink_grace_s``: before committing the shrink, the
      SupervisedCluster polls engine liveness for this long — a
      flapping executor that returns inside the grace keeps the
      original width (reform, not resize).
    - Regrow: during an attempt running below ``max_width`` (default:
      the job's configured width), the SupervisedCluster probes engine
      capacity every ``regrow_probe_s``; when spare executors exist it
      requests a BOUNDARY DRAIN — every trainer raises
      :class:`ResizeDrain` at its next ``TrainerSide.step`` site,
      which is AFTER that step's checkpoint committed and its
      partition was acked, so the reform up is exactly-once by the
      same argument as the chaos kill site.
    - Other failure kinds (trainer crash at intact width) fall back to
      the inherited same-width RestartFromCheckpoint behavior.

    ``max_restarts`` bounds ALL recovery reforms (shrinks included) so
    a flapping fleet cannot reform forever.
    """

    def __init__(self, min_width=1, max_width=None, shrink_grace_s=0.0,
                 regrow_probe_s=0.5, max_restarts=8, **kw):
        super(ElasticResize, self).__init__(max_restarts=max_restarts,
                                            **kw)
        self.min_width = int(min_width)
        self.max_width = None if max_width is None else int(max_width)
        self.shrink_grace_s = float(shrink_grace_s)
        self.regrow_probe_s = float(regrow_probe_s)

    def decide(self, event, restarts, failure_counts, excluded,
               num_executors, width=None):
        base = super(ElasticResize, self).decide(
            event, restarts, failure_counts, excluded, num_executors)
        if base.action == Decision.FAIL:
            return base
        if event.kind not in ("executor_lost", "reform_failed"):
            return base  # intact width: plain restart-from-checkpoint
        width = int(width) if width is not None \
            else num_executors - len(excluded)
        target = width - 1
        if target < self.min_width:
            return Decision(
                Decision.FAIL,
                reason="cannot shrink below min_width={} (width was "
                       "{})".format(self.min_width, width))
        return Decision(
            Decision.RESIZE, width=target,
            reason="{}; shrinking {} -> {} (no replacement "
                   "awaited)".format(base.reason, width, target))


class ResizeDrain(RuntimeError):
    """Raised by ``TrainerSide.step`` when the driver requested a
    boundary drain (elastic regrow): the trainer exits AT the
    checkpoint boundary — the just-committed step is restorable and
    its partition acked — so the reform up to the new width replays
    exactly the unconsumed remainder. Supervision-aware map_funs let
    it propagate (the supervisor treats the resulting attempt end as
    planned, not as a failure)."""


class RestartEngine(object):
    """Serving-plane recovery policy for :meth:`Supervisor.watch`: when
    a watched ``DecodeEngine``'s scheduler dies (uncaught loop error —
    NOT a deliberate stop/drain), rebuild the engine from its own
    construction config (``engine.respawn()``) with bounded exponential
    backoff and re-arm the ``ModelServer``, instead of leaving the
    replica answering 503 forever.

    The dying loop already failed every outstanding handle with the
    retriable ``serving.EngineFailed`` (clients retry; HTTP surfaces it
    as 503 + Retry-After), so a restart only has to bring the engine
    back for FRESH requests; ``tracing.Counters``' ``engine_restarts``
    counts the rebuilds (the respawned engine shares the dead one's
    counters). ``max_restarts`` bounds rebuilds per watch entry; when
    exhausted the server is marked unhealthy permanently — the same
    terminal state as an unwatched death, reached honestly.
    """

    def __init__(self, max_restarts=3, backoff=0.5, backoff_factor=2.0,
                 max_backoff=30.0):
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff = float(max_backoff)

    def decide(self, restarts):
        if restarts >= self.max_restarts:
            return Decision(
                Decision.FAIL,
                reason="gave up after {} engine restart(s)".format(restarts))
        delay = min(self.backoff * self.backoff_factor ** restarts,
                    self.max_backoff)
        return Decision(Decision.RESTART, delay=delay,
                        reason="engine restart {} of {}".format(
                            restarts + 1, self.max_restarts))


class SupervisorConfig(object):
    """Knobs for the supervision plane.

    Args:
      policy: recovery policy (default :class:`RestartFromCheckpoint`).
      heartbeat_interval: seconds between node heartbeat-lease beats
        (shipped to nodes via cluster_meta).
      heartbeat_timeout: lease age classified as executor loss. Must
        comfortably exceed the interval; 5x is a sane floor.
      stall_timeout: seconds of frozen feed progress (with a live
        trainer) classified as feeder stall / ring wedge. Set it above
        the slowest legitimate step time.
      poll_interval: monitor classification cadence.
      classify_grace: how long a surfaced task error waits for the
        monitor to attribute it to a lease before a generic event is
        synthesized.
      shutdown_timeout / drain_timeout: bounds on attempt teardown and
        post-abort job drain — a recovery must never hang on the very
        wedge it is recovering from.
      straggler_skew: step-time skew (executor effective step time /
        fleet lower-median) at which an OBSERVE-ONLY ``straggler``
        incident is raised (goodput.StragglerDetector; None disables).
        Incidents never reach the recovery policy — skew is a capacity
        signal, not a failure.
      straggler_min_stall_s: floor below which a frozen step counter
        is not substituted for the EWMA (checkpoint pauses must not
        read as stalls).
    """

    def __init__(self, policy=None, heartbeat_interval=1.0,
                 heartbeat_timeout=15.0, stall_timeout=120.0,
                 poll_interval=0.5, classify_grace=3.0,
                 shutdown_timeout=120.0, drain_timeout=60.0,
                 straggler_skew=3.0, straggler_min_stall_s=5.0):
        self.policy = policy if policy is not None else RestartFromCheckpoint()
        self.heartbeat_interval = float(heartbeat_interval)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.stall_timeout = float(stall_timeout)
        self.poll_interval = float(poll_interval)
        self.classify_grace = float(classify_grace)
        self.shutdown_timeout = float(shutdown_timeout)
        self.drain_timeout = float(drain_timeout)
        self.straggler_skew = None if straggler_skew is None \
            else float(straggler_skew)
        self.straggler_min_stall_s = float(straggler_min_stall_s)


class Supervisor(object):
    """Driver-side monitor: aggregates leases, classifies failures,
    tracks recovery milestones, and watches serving engines.

    One instance supervises one cluster *attempt* (bound to that
    attempt's reservation ``Server``); the shared :class:`tracing
    .EventLog` carries the timeline across attempts. Also usable
    standalone (``Supervisor()``) purely as an engine watcher via
    :meth:`watch`.
    """

    def __init__(self, server=None, executors=(), config=None, events=None,
                 attempt=1, alive_fn=None, incidents=None):
        self.server = server
        self.executors = list(executors)
        self.config = config or SupervisorConfig()
        self.events = events if events is not None else tracing.EventLog()
        self.attempt = attempt
        #: OBSERVE-ONLY incidents (straggler skew): recorded with
        #: evidence like failures, but NEVER fed to a recovery policy.
        #: A SupervisedCluster passes one shared list so incidents
        #: survive across attempts (the EventLog idiom).
        self._incidents = incidents if incidents is not None else []
        self._straggler = None
        if self.config.straggler_skew is not None:
            self._straggler = goodput_mod.StragglerDetector(
                skew_threshold=self.config.straggler_skew,
                min_stall_s=self.config.straggler_min_stall_s)
        #: optional engine liveness view (Context.executors_alive): an
        #: executor whose process the ENGINE has already seen die is
        #: classified executor_lost immediately instead of waiting out
        #: heartbeat_timeout — the detect-stage win the elastic shrink
        #: MTTR leg measures
        self.alive_fn = alive_fn
        self._lock = threading.Lock()
        self._failures = []
        self._failure_evt = threading.Event()
        self._reported = set()      # executor ids already attributed
        self._progress = {}         # eid -> (feed_hb value, t of change)
        self._restored_step = None
        self._restored_seen = False
        self._first_step_seen = False
        self._watched = []          # serving engines under watch
        self._serving_watch = None  # executor-hosted fleet lease watch
        self._stop = threading.Event()
        self._thread = None
        self._started = time.monotonic()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="tfos-supervisor", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("supervisor poll failed")
            self._stop.wait(self.config.poll_interval)

    # -- classification --------------------------------------------------

    def poll_once(self, now=None):
        """One classification pass (the monitor thread's body; exposed
        so unit tests drive it deterministically without the thread)."""
        now = now if now is not None else time.monotonic()
        self._classify_engine_liveness()
        if self.server is not None:
            leases = self.server.lease_snapshot()
            for event in self._classify(leases, now):
                self._report(event)
            self._track_recovery(leases)
            self._classify_stragglers(leases, now)
        self._check_watched()
        self._check_serving_leases()

    def _classify_engine_liveness(self):
        """Fast-path executor-lost detection from the engine's own
        liveness view: a lost connection is definitive (and near
        instant) evidence, so don't wait out heartbeat_timeout for the
        lease to age. Lease classification remains the backstop for
        engines without the view (Spark) and for processes that go
        dark without dying."""
        if self.alive_fn is None:
            return
        try:
            alive = set(self.alive_fn())
        except Exception:  # noqa: BLE001 - liveness view is best-effort
            return
        for eid in self.executors:
            if eid not in self._reported and eid not in alive:
                self._report(FailureEvent(
                    "executor_lost", eid,
                    "engine reports the executor process gone "
                    "(connection lost)"))

    def _classify(self, leases, now):
        """Lease snapshot -> new FailureEvents (one per executor, ever:
        an executor already attributed stays attributed)."""
        events = []
        cfg = self.config
        for eid in self.executors:
            if eid in self._reported:
                continue
            lease = leases.get(eid)
            if lease is None:
                # never beat at all: only suspicious once formation slack
                # has passed (the barrier opened before we were built, so
                # the first beat should land within one timeout)
                if now - self._started > cfg.heartbeat_timeout:
                    events.append(FailureEvent(
                        "executor_lost", eid,
                        "no heartbeat lease registered within "
                        "{:.0f}s".format(cfg.heartbeat_timeout)))
                continue
            payload = lease["payload"]
            state = payload.get("state")
            if state == "stopped":
                # Node lifecycle completed cleanly: nothing to classify.
                continue
            if lease["age"] > cfg.heartbeat_timeout \
                    and state != "terminating":
                # 'terminating' leases age out BY DESIGN: teardown
                # silences the beat thread after one final synchronous
                # beat, so a finished node aging past heartbeat_timeout
                # during a slow sibling's shutdown is NOT executor loss
                # (misattributing it would poison Blacklist's
                # failure_counts for a healthy executor). Crash evidence
                # carried by that final beat still classifies below.
                events.append(FailureEvent(
                    "executor_lost", eid,
                    "heartbeat lease expired (age {:.1f}s > "
                    "{:.0f}s)".format(lease["age"], cfg.heartbeat_timeout),
                    payload))
                continue
            exit_code = payload.get("trainer_exit")
            if exit_code not in (None, 0):
                events.append(FailureEvent(
                    "trainer_crash", eid,
                    "trainer exited with code {}".format(exit_code),
                    payload))
                continue
            if state == "error":
                events.append(FailureEvent(
                    "trainer_crash", eid, "node state is 'error'", payload))
                continue
            if payload.get("trainer_alive") is False and exit_code is None \
                    and state == "running":
                events.append(FailureEvent(
                    "trainer_crash", eid,
                    "trainer process dead with no exit status", payload))
                continue
            hb = payload.get("feed_hb")
            if hb is None or state != "running":
                continue
            prev = self._progress.get(eid)
            if prev is None or prev[0] != hb:
                self._progress[eid] = (hb, now)
            elif now - prev[1] > cfg.stall_timeout:
                kind = "ring_wedge" \
                    if payload.get("feed_transport") == "shm" \
                    else "feeder_stall"
                events.append(FailureEvent(
                    kind, eid,
                    "feed progress frozen at {} batches for {:.0f}s "
                    "with a live trainer".format(hb, now - prev[1]),
                    payload))
        return events

    def _report(self, event):
        with self._lock:
            if event.executor_id is not None:
                self._reported.add(event.executor_id)
            self._failures.append(event)
        self.events.record("failure_detected", attempt=self.attempt,
                           kind=event.kind, executor=event.executor_id,
                           detail=event.detail)
        # black-box postmortem (PR 5): every classified failure carries
        # the flight recorder's recent tail — for a chaos run that is
        # the last thing each plane did before the incident, dumped
        # automatically instead of reconstructed from logs. Taken AFTER
        # the failure_detected record above, so the incident's own
        # classification instant is part of its dump.
        if "flight" not in event.payload:
            event.payload["flight"] = tracing.flight_recorder().tail(64)
        logger.error("supervisor detected failure: %s", event)
        self._failure_evt.set()

    def _track_recovery(self, leases):
        """Record the restore / first-post-restore-step milestones the
        MTTR stage breakdown is computed from."""
        for eid, lease in leases.items():
            payload = lease["payload"]
            restored = payload.get("restored_step")
            if restored is not None and not self._restored_seen:
                self._restored_seen = True
                self._restored_step = int(restored)
                self.events.record("restored", attempt=self.attempt,
                                   step=int(restored), executor=eid)
            step = payload.get("train_step")
            if step is not None and self._restored_seen \
                    and not self._first_step_seen \
                    and int(step) > (self._restored_step or 0):
                self._first_step_seen = True
                self.events.record("first_step", attempt=self.attempt,
                                   step=int(step), executor=eid)

    def _classify_stragglers(self, leases, now):
        """Observe-only skew detection (goodput plane): an executor
        whose effective step time (BEAT-carried EWMA, or its frozen
        step counter's age) exceeds the configured skew vs the fleet
        median raises a ``straggler`` INCIDENT — recorded with the
        offender's beat-carried metrics snapshot and the flight tail
        as evidence, exactly like a failure's, but never handed to a
        recovery policy: skew asks for an operator (or an autoscaler),
        not a restart."""
        if self._straggler is None:
            return
        # beats that STOPPED are a liveness problem, not a skew
        # signal: a dead node's frozen step counter would otherwise
        # read as a stall and fire a spurious straggler before the
        # heartbeat-timeout classification reports it lost (and its
        # inflated stall age would skew the median used to judge
        # genuinely slow survivors)
        stale_after = max(3 * self.config.heartbeat_interval, 3.0)
        views = {}
        for eid, lease in leases.items():
            payload = lease["payload"]
            if payload.get("role") == "serving":
                continue  # serving replicas have no train steps
            if lease.get("age", 0.0) > stale_after:
                continue  # beats stopped: liveness owns this executor
            if payload.get("state") in ("terminating", "stopped",
                                        "error") \
                    or payload.get("trainer_alive") is False:
                continue  # dying/dead: crash classification owns it
            if eid in self._reported:
                continue  # already attributed as a failure
            views[eid] = {"metrics": payload.get("metrics"),
                          "train_step": payload.get("train_step")}
        for finding in self._straggler.observe(views, now=now):
            eid = finding["executor_id"]
            payload = leases.get(eid, {}).get("payload", {})
            event = FailureEvent(
                "straggler", eid,
                "step time {}x the fleet median ({:.3f}s vs "
                "{:.3f}s{})".format(
                    finding["skew"], finding["effective_s"],
                    finding["median_s"],
                    "; step counter frozen" if finding["stalled"]
                    else ""),
                dict(payload))
            self._report_incident(event, finding)

    def _report_incident(self, event, detail=None):
        """Record an observe-only incident: evidence attached like
        :meth:`_report`'s, EventLog milestone recorded, but the event
        goes to :meth:`incidents` — never to the failure list the
        recovery loop drains."""
        self.events.record("incident", attempt=self.attempt,
                           kind=event.kind, executor=event.executor_id,
                           detail=event.detail)
        if "flight" not in event.payload:
            event.payload["flight"] = tracing.flight_recorder().tail(64)
        incident = event.as_dict()
        if detail:
            incident["detail_fields"] = dict(detail)
        with self._lock:
            self._incidents.append(incident)
        logger.warning("supervisor incident (observe-only): %s", event)

    def record_slo_incident(self, kind, detail, payload=None):
        """Public observe-only incident entry point for the serving SLO
        plane (:mod:`tensorflowonspark_tpu.slo`): a burn-rate raise or
        canary drift lands in :meth:`incidents` with the standard
        evidence schema (payload + flight-recorder tail), never in the
        failure list the recovery loop drains — an SLO page is a human
        signal, not a restart trigger."""
        self._report_incident(
            FailureEvent(kind, "serving", detail, dict(payload or {})))

    def incidents(self):
        """Observe-only incidents recorded so far (straggler skew,
        serving SLO burn/drift); each carries the same evidence schema
        as a failure."""
        with self._lock:
            return list(self._incidents)

    # -- failure access --------------------------------------------------

    def first_failure(self):
        with self._lock:
            return self._failures[0] if self._failures else None

    def failures(self):
        with self._lock:
            return list(self._failures)

    def wait_for_failure(self, timeout):
        self._failure_evt.wait(timeout)
        return self.first_failure()

    # -- serving-plane watch ---------------------------------------------

    def watch(self, engine, server=None, restart=None, router=None,
              replica=None):
        """Watch a serving ``DecodeEngine``; when its scheduler thread
        dies (or the engine breaks), mark ``server`` (a ``ModelServer``)
        unhealthy so ``GET /healthz`` answers 503 — a dead scheduler
        must not leave the HTTP surface answering as if healthy.

        ``restart`` (a :class:`RestartEngine`) upgrades the response
        from mark-and-abandon to RECOVER: the dead engine is stopped,
        rebuilt via ``engine.respawn()`` after the policy's backoff,
        and re-armed on ``server`` (``attach_engine`` clears the
        unhealthy mark, /healthz returns to 200). Deliberate deaths —
        ``stop()`` / ``drain()`` flip ``stopping`` first — are never
        resurrected: an operator retiring a replica must not fight its
        own supervisor.

        Fleet plane (PR 6): ``router`` (a ``fleet.FleetRouter``) is
        told to STOP ROUTING first — ``router.quiesce(replica_id)``
        lands before any restart work, so no fresh request races into
        the rebuild window — and readmitted only after a successful
        re-arm. ``replica`` (a ``fleet.Replica``) keeps the watch
        following the replica's CURRENT engine when something else
        swaps it (a rolling-drain upgrade re-points the watch at the
        successor instead of leaving it staring at a deliberately
        drained corpse)."""
        self._watched.append({"engine": engine, "server": server,
                              "restart": restart, "router": router,
                              "replica": replica, "restarts": 0,
                              "dead": False})
        self.start()
        return self

    def watch_fleet(self, fleet, restart=None):
        """Watch every IN-PROCESS replica of a ``fleet.ServingFleet``:
        a dead replica scheduler quiesces that replica at the router
        FIRST, then restarts through :class:`RestartEngine` (default
        policy; pass your own to re-tune), then readmits. One entry
        per replica, all driven by this supervisor's monitor thread.
        Executor-hosted replicas have no driver-side engine object to
        poll — they are covered by :meth:`watch_serving`'s lease
        classification instead."""
        for replica in fleet.replicas:
            if getattr(replica, "remote", False):
                continue
            self.watch(replica.engine, server=replica.server,
                       restart=restart if restart is not None
                       else RestartEngine(),
                       router=fleet.router, replica=replica)
        return self

    def watch_serving(self, fleet, stale_after=1.0):
        """Attribute EXECUTOR-HOSTED replica death (PR 13): classify
        the fleet's serving BEAT leases the way cluster supervision
        classifies trainer leases. A replica whose lease expired
        (SIGKILLed executor — the beat died with the process) or whose
        lease says the engine is dead is quiesced at the router and
        reported ONCE per episode as an attributed ``serving_replica_
        lost`` failure with the last lease payload as evidence. No
        RestartEngine budget burns here — the driver cannot respawn an
        engine inside a dead executor; repair belongs to the
        autoscaler's replacement path (same identity, fresh fencing
        epoch), and a FENCED corpse that resurfaces is deliberately
        ignored (its replacement is the live story). Keep
        ``stale_after`` BELOW the autoscale policy's ``dead_after_s``
        (default 1.0 vs 3.0) so the attributed incident lands before
        the repair erases its evidence. Recovery —
        the lease returning fresh under a live engine — re-arms the
        episode and readmits nothing itself (the replacement path's
        wire-verified readmit already did)."""
        self._serving_watch = {"fleet": fleet,
                               "stale_after": float(stale_after),
                               "reported": set()}
        self.start()
        return self

    def _check_serving_leases(self):
        watch = self._serving_watch
        if watch is None:
            return
        fleet = watch["fleet"]
        recovering = getattr(fleet.reservation, "recovering",
                             None)  # stub reservations lack it
        if recovering is not None and recovering():
            # control-plane recovery grace (PR 19): a restarted
            # journal-seeded reservation server knows the FLOORS but
            # has not heard the incumbents re-announce yet — every
            # lease looks expired for a beat interval or two. Those
            # are recovery artifacts, not deaths; classifying them
            # now would quiesce (and incident-report) a fleet of
            # perfectly healthy replicas.
            return
        snapshot = fleet.reservation.serving_snapshot()
        for replica in list(fleet.replicas):
            if not getattr(replica, "remote", False):
                continue
            rid = replica.replica_id
            info = snapshot.get(rid)
            age = (info or {}).get("age")
            gauges = (info or {}).get("serving") or {}
            epoch = (info or {}).get("epoch")
            current = fleet.reservation.lease_epoch(rid)
            if epoch is not None and current is not None \
                    and epoch < current:
                # superseded incarnation (replacement in flight or
                # already serving): the corpse's lease is history,
                # not a fresh failure
                continue
            dead = age is None or age > watch["stale_after"] \
                or gauges.get("alive") is False
            if dead and rid not in watch["reported"]:
                watch["reported"].add(rid)
                reason = ("serving lease expired (age {}s > {}s) — "
                          "executor presumed lost".format(
                              round(age, 2) if age is not None else None,
                              watch["stale_after"])
                          if age is None or age > watch["stale_after"]
                          else "lease fresh but engine dead")
                if fleet.router is not None:
                    fleet.router.quiesce(rid, reason, owner="supervisor")
                self.events.record("serving_replica_lost", replica=rid,
                                   executor=replica.executor_id,
                                   reason=reason)
                self._report(FailureEvent(
                    "serving_replica_lost", None,
                    "replica {} (executor {}): {}".format(
                        rid, replica.executor_id, reason),
                    payload={"lease": info, "replica": rid}))
            elif not dead and rid in watch["reported"]:
                watch["reported"].discard(rid)
                if fleet.router is not None:
                    # release OUR hold (owner-scoped): the lease
                    # recovered WITHOUT a replacement — a beat stall,
                    # not a death — so spawn_replica's force-clear
                    # will never run, and an unreleased supervisor
                    # quiesce would hold a healthy replica out of
                    # routing forever (a 1-replica fleet: 503s
                    # despite a live, beating replica)
                    fleet.router.readmit(rid, owner="supervisor")
                self.events.record("serving_replica_recovered",
                                   replica=rid)

    def _check_watched(self):
        for entry in self._watched:
            replica = entry.get("replica")
            if replica is not None and replica.engine is not None \
                    and replica.engine is not entry["engine"]:
                # the replica's engine was swapped out from under the
                # watch (rolling-drain upgrade / manual attach_engine):
                # follow the successor — the old corpse is retired by
                # design and must not trip a death report. HEAL only
                # the marks THIS WATCH applied (gated on "marked", and
                # the router hold is owner-scoped): a poll that read
                # the dying engine could have quiesced the router /
                # marked the server unhealthy AFTER the swapper's own
                # attach+readmit, which would otherwise strand a
                # healthy replica administratively DOWN forever. A
                # rolling drain's OWN hold is untouched — it releases
                # only after its wire-verified /healthz, so the heal
                # can never readmit an unverified successor on the
                # drain's behalf.
                entry["engine"] = replica.engine
                entry["dead"] = False
                if entry.pop("marked", False):
                    rid = getattr(entry["engine"], "replica_id", None)
                    if entry.get("router") is not None \
                            and rid is not None:
                        entry["router"].readmit(rid, owner="supervisor")
                    if entry.get("server") is not None:
                        entry["server"].attach_engine(entry["engine"])
            if replica is not None and getattr(replica, "fenced", False):
                # lease fencing (PR 12): a FENCED replica is
                # administratively superseded — another holder owns its
                # identity's current epoch. Its engine's liveness is
                # irrelevant until a deliberate re_register(), and a
                # RestartEngine respawn here would burn restart budget
                # reviving a scheduler behind a server answering 410.
                # Report once per fence episode, then stand down.
                if not entry.get("fence_reported"):
                    entry["fence_reported"] = True
                    rid = getattr(entry["engine"], "replica_id", None)
                    self.events.record("replica_fenced", replica=rid)
                    self._report(FailureEvent(
                        "replica_fenced", None,
                        "replica {} fenced (stale lease epoch); "
                        "supervision suspended until re_register"
                        .format(rid)))
                continue
            entry.pop("fence_reported", None)  # re-registered: resume
            if entry["dead"]:
                continue
            health = entry["engine"].healthy()
            if health.get("alive"):
                continue
            if replica is not None and replica.engine is not None \
                    and replica.engine is not entry["engine"]:
                # the engine was swapped between the health read and
                # now (rolling drain racing this poll): do nothing —
                # the next poll's swap branch follows and heals
                continue
            entry["dead"] = True
            reason = "decode engine scheduler dead: {}".format(
                health.get("broken") or
                ("stopped" if health.get("stopping")
                 else "scheduler thread exited"))
            rid = getattr(entry["engine"], "replica_id", None)
            if entry.get("router") is not None and rid is not None:
                # fleet ordering contract: the router stops routing to
                # this replica BEFORE any recovery work, so the rebuild
                # window never absorbs fresh traffic. "marked" records
                # that this watch placed marks, so the swap-heal branch
                # above knows they are its own to clear
                entry["router"].quiesce(rid, reason, owner="supervisor")
                entry["marked"] = True
            self.events.record("engine_dead", reason=reason,
                               replica=rid)
            # evidence: the ENGINE's flight recorder tail — the spans
            # of the very requests in flight when the scheduler died
            flight = getattr(entry["engine"], "flight", None)
            self._report(FailureEvent(
                "engine_dead", None, reason,
                payload=None if flight is None
                else {"flight": flight.tail(64)}))
            if entry["restart"] is not None \
                    and not health.get("stopping") \
                    and not health.get("draining") \
                    and hasattr(entry["engine"], "respawn"):
                # draining counts as deliberate too: an engine that
                # crashes MID-DRAIN belongs to the operator retiring
                # it (ModelServer.drain is about to stop the server) —
                # respawning it would leak a fresh scheduler against a
                # server that is going away
                self._restart_engine(entry, reason)
                continue
            if entry["server"] is not None:
                entry["server"].mark_unhealthy(reason)
                entry["marked"] = True

    def _restart_engine(self, entry, reason):
        """Drive one RestartEngine recovery: decide -> backoff ->
        stop the corpse -> respawn -> re-arm, retrying failed respawns
        INSIDE this call until the policy exhausts. The retry loop must
        live here, not across polls: stopping the corpse flips its
        ``stopping`` flag, so a later poll would read the death as
        deliberate and silently disable recovery with restart budget
        remaining. Runs on the monitor thread (backoff + retries pause
        other classification — acceptable for a serving-only
        supervisor; use one Supervisor per concern if that bites)."""
        server = entry["server"]
        old = entry["engine"]
        while not self._stop.is_set():
            decision = entry["restart"].decide(entry["restarts"])
            if decision.action != Decision.RESTART:
                self.events.record("engine_restart_exhausted",
                                   reason=decision.reason)
                if server is not None:
                    server.mark_unhealthy(
                        "{} ({})".format(reason, decision.reason))
                    entry["marked"] = True
                return
            if server is not None:
                # 503 for the rebuild window: a restart takes real time
                # (backoff + engine construction) and the LB must not
                # route into it
                server.mark_unhealthy(
                    "engine restarting: {}".format(reason))
                entry["marked"] = True
            if decision.delay:
                logger.info("engine restart backing off %.1fs",
                            decision.delay)
                if self._stop.wait(decision.delay):
                    return  # supervisor stopped mid-backoff
            entry["restarts"] += 1
            try:
                # stop() joins the (dead) scheduler and fails any
                # handle the corpse still holds; respawn() rebuilds
                # from the engine's own construction config, sharing
                # its counters
                old.stop()
                fresh = old.respawn()
            except Exception as e:  # noqa: BLE001 - policy bounds retries
                logger.exception("engine respawn failed")
                self.events.record("engine_restart_failed", error=str(e))
                continue  # decide again: next attempt or exhaustion
            entry["engine"] = fresh
            entry["dead"] = False
            fresh.counters.inc("engine_restarts")
            if server is not None:
                server.attach_engine(fresh)
            rid = getattr(fresh, "replica_id", None)
            if entry.get("router") is not None and rid is not None:
                # re-arm order: engine attached (healthz back to 200)
                # BEFORE the router resumes routing to this replica;
                # releases only the supervisor's own hold
                entry["router"].readmit(rid, owner="supervisor")
            entry["marked"] = False
            self.events.record("engine_restarted",
                               restarts=entry["restarts"], reason=reason)
            logger.warning("decode engine restarted (restart %d): %s",
                           entry["restarts"], reason)
            return

    # -- remote abort ----------------------------------------------------

    def abort_attempt(self, cluster_info, cluster_meta, reason):
        """Flip every node's broker state to 'error' so blocked feeders,
        joins, and DataFeed consumers unwind (their bounded waits all
        check state) — the driver's only lever against a wedge that will
        never surface a task error on its own. Best effort per node."""
        import multiprocessing

        from tensorflowonspark_tpu import manager
        authkey = bytes.fromhex(cluster_meta["authkey"])
        multiprocessing.current_process().authkey = authkey
        for node_meta in cluster_info:
            try:
                mgr = manager.connect(tuple(node_meta["mgr_addr"]), authkey)
                try:
                    mgr.get_queue("error").put(
                        "supervisor abort: {}".format(reason), block=False)
                except Exception:  # noqa: BLE001 - error queue may be full
                    pass
                mgr.set("state", "error")
            except Exception:  # noqa: BLE001 - node may be gone entirely
                logger.debug("abort could not reach executor %s",
                             node_meta.get("executor_id"), exc_info=True)


# -- supervised feed closures (run on executors) ---------------------------

def _drain_iter(iterator):
    for _ in iterator:
        pass


def acked_feed(cluster_info, cluster_meta, acked, feed_timeout=600,
               qname="input"):
    """Feed closure for ``mapPartitionsWithIndex``: feeds a partition to
    the local node and ACKs it against the reservation server once the
    node consumed it; partitions in ``acked`` (consumed by a previous
    attempt) are drained without feeding — the replay-only-unacked
    mechanic of RestartFromCheckpoint."""
    acked = frozenset(acked)

    def _fn(idx, iterator):
        from tensorflowonspark_tpu import node as node_mod
        from tensorflowonspark_tpu import reservation as reservation_mod
        if idx in acked:
            for _ in iterator:
                pass
            return iter(())
        consumed = node_mod._feed_one_partition(
            iterator, cluster_info, cluster_meta, feed_timeout, qname)
        if consumed:
            client = reservation_mod.Client(cluster_meta["server_addr"])
            try:
                client.ack(idx)
            finally:
                client.close()
        return iter(())

    return _fn


# -- trainer-side helpers --------------------------------------------------

class TrainerSide(object):
    """Trainer-process handle publishing recovery milestones.

    Writes ``restored_step`` / ``train_step`` into the node's broker kv,
    which the heartbeat lease carries to the driver — how the supervisor
    sees "restore finished" and "first post-restore step" without any
    new channel. Also hosts the chaos kill-at-step injection site, AFTER
    the step (and its checkpoint) committed, so a killed step N is
    restorable at N.
    """

    #: seconds between resize_drain polls in :meth:`step` — the drain
    #: check is one extra broker RPC, so fast step loops only pay it
    #: ~4x/second instead of per step; a pending drain is still caught
    #: at a step boundary, just up to this much later
    drain_poll_interval = 0.25

    #: seconds between forced metrics flushes in :meth:`step` (goodput
    #: plane): the step boundary force-publishes the feed registry —
    #: which carries the process goodput ledger — BEFORE the chaos
    #: kill site, so a killed trainer's accounting is current to
    #: within this throttle instead of the feed's 2s heartbeat window
    metrics_flush_interval = 0.5

    def __init__(self, mgr, restored_step=None, feed=None):
        self.mgr = mgr
        self.feed = feed
        self._drain_checked = float("-inf")
        self._flushed = float("-inf")
        if restored_step is not None:
            self.report_restore(restored_step)

    def report_restore(self, step):
        self.mgr.set("restored_step", int(step))
        self.mgr.set("train_step", int(step))

    def step(self, step):
        from tensorflowonspark_tpu import chaos
        self.mgr.set("train_step", int(step))
        now = time.monotonic()
        if self.feed is not None \
                and now - self._flushed >= self.metrics_flush_interval:
            # flush BEFORE the kill site: a step-N kill must not lose
            # step N's goodput charges to the heartbeat throttle
            self._flushed = now
            try:
                self.feed.publish_metrics()
            except Exception:  # noqa: BLE001 - accounting best-effort
                pass
        chaos.on_step(int(step))
        # elastic regrow: the step site IS the checkpoint boundary
        # (callers publish AFTER the step's checkpoint committed and
        # its partition acked — the same discipline the chaos kill
        # site rides), so a driver-requested boundary drain exits here
        # and the reform up is exactly-once by construction
        now = time.monotonic()
        if now - self._drain_checked < self.drain_poll_interval:
            return
        self._drain_checked = now
        target = self.mgr.get("resize_drain")
        if target is not None:
            raise ResizeDrain(
                "resize drain requested at step {} (reforming at "
                "width {})".format(int(step), target))

    def hook(self, base=0):
        """``Trainer.train_loop`` hook: publishes ``base + step_no``."""
        def _hook(step_no, state, metrics):
            self.step(base + step_no)
        return _hook


def attach(ctx, restored_step=None, feed=None):
    """Supervision-aware map_fun boilerplate::

        restored = ckpt.restore(state, fallback=True)
        start = 0 if restored is None else int(restored["step"])
        sup = supervisor.attach(ctx, restored_step=start, feed=feed)
        ...
        sup.step(int(state["step"]))   # after each step's checkpoint

    ``feed`` (the map_fun's DataFeed): lets the step boundary
    force-flush the metrics/goodput snapshot before the chaos kill
    site — tighter accounting across a kill, optional otherwise."""
    return TrainerSide(ctx.mgr, restored_step=restored_step, feed=feed)


# -- MTTR extraction -------------------------------------------------------

def recovery_stages(events, kill_wall=None):
    """MTTR stage breakdown from a supervision :class:`tracing.EventLog`.

    Stages (seconds, None when the span's endpoints are absent):
    ``detect`` (fault injection -> failure_detected; needs ``kill_wall``,
    e.g. a chaos fuse's fire time), ``reform`` (failure_detected ->
    cluster_formed), ``restore`` (cluster_formed -> restored), and
    ``first_step`` (restored -> first post-restore step). ``mttr_s`` is
    fault->first_step when ``kill_wall`` is known, else
    detection->first_step.
    """
    detected = events.last("failure_detected")
    if detected is None:
        return None

    def _after(name):
        for event in events.events(name):
            if event["t"] >= detected["t"]:
                return event
        return None

    formed = _after("cluster_formed")
    restored = _after("restored")
    first = _after("first_step")

    def _span(a, b):
        return None if a is None or b is None else round(b["t"] - a["t"], 3)

    out = {
        "detect_s": None if kill_wall is None
        else round(detected["wall"] - kill_wall, 3),
        "reform_s": _span(detected, formed),
        "restore_s": _span(formed, restored),
        "first_step_s": _span(restored, first),
    }
    if first is not None:
        out["mttr_s"] = round(first["wall"] - kill_wall, 3) \
            if kill_wall is not None else round(first["t"] - detected["t"], 3)
    else:
        out["mttr_s"] = None
    return out


# -- the supervised cluster lifecycle --------------------------------------

class SupervisedCluster(object):
    """``cluster.run(..., supervise=cfg)``'s return value: the familiar
    ``train``/``shutdown`` surface with the detect->decide->recover loop
    inside.

    Built-in-engine semantics: attempts reform clusters on the same
    executor processes (a dead trainer is a child process; the executor
    survives), and :class:`Blacklist` exclusions route jobs away from an
    executor without restarting the engine. InputMode.SPARK jobs replay
    only unacked feed partitions; InputMode.TENSORFLOW jobs resubmit the
    whole (self-reading) map_fun, which restores from its checkpoint.
    """

    def __init__(self, sc, map_fun, tf_args, num_executors, config=None,
                 run_kwargs=None):
        from tensorflowonspark_tpu import cluster as cluster_mod
        self._cluster_mod = cluster_mod
        self.sc = sc
        self.map_fun = map_fun
        self.tf_args = tf_args
        self.num_executors = int(num_executors)
        self.config = config if isinstance(config, SupervisorConfig) \
            else SupervisorConfig()
        self.run_kwargs = dict(run_kwargs or {})
        self.input_mode = self.run_kwargs.get(
            "input_mode", cluster_mod.InputMode.SPARK)
        self.events = tracing.EventLog()
        self.excluded = set()
        self.failure_counts = {}
        self.attempts = []          # one dict per FAILED attempt
        self.formations = 0
        #: the ONE width source of truth (elastic resize): every
        #: formation is exactly this wide. Blacklist exclusions and
        #: RESIZE decisions both update it (and record width_change),
        #: so /metrics' tfos_cluster_width gauge, the EventLog, and the
        #: formation math can never disagree.
        self.width = int(num_executors)
        self._resize_target = None  # planned regrow width, drain sent
        self._last_probe = 0.0
        self._acked = set()
        self._last_metrics = None   # rollup harvested before teardown
        #: goodput plane (goodput.py): the DRIVER's ledger charges only
        #: the windows no trainer exists to measure — reform (detect/
        #: teardown/backoff/formation) and planned resize-drain
        #: teardown; everything inside a live attempt is accounted by
        #: the executors' own ledgers, harvested per attempt below and
        #: folded by goodput_report()
        self.goodput = goodput_mod.GoodputLedger()
        self._goodput_wall_s = None  # frozen at job completion/failure
        self._attempt_rollups = {}  # formation ordinal -> last rollup
        self._next_form_category = "reform"
        #: observe-only incidents (straggler skew), shared across every
        #: attempt's Supervisor like the EventLog
        self.incidents = []
        self._tfc = None
        self._supervisor = None
        self._done = False
        self.events.record("job_start", num_executors=self.num_executors)
        self._form()

    # -- public surface --------------------------------------------------

    @property
    def cluster_info(self):
        return self._tfc.cluster_info if self._tfc is not None else None

    def tensorboard_url(self):
        return self._tfc.tensorboard_url() if self._tfc is not None else None

    def metrics(self):
        """Cluster-wide observability rollup (``TFCluster.metrics``
        shape): per-executor beat-carried feed-stage + step-rate series
        plus the merged cluster view. Live while an attempt is running;
        after shutdown (or between attempts) the view harvested from
        the last live cluster is returned, so a completed supervised
        job can still report what its executors measured. Safe against
        a concurrent teardown (the recovery loop nulls ``_tfc``): a
        harvest that loses that race just returns the previous view."""
        self._harvest_metrics()
        return self._last_metrics

    def metrics_url(self):
        """The live attempt's driver-side OpenMetrics URL
        (``TFCluster.metrics_url``), or None between attempts / after
        shutdown (each reformation binds a fresh stats port)."""
        tfc = self._tfc
        return tfc.metrics_url() if tfc is not None else None

    def train(self, dataRDD, num_epochs=0, feed_timeout=600, qname="input"):
        """Supervised feed: like ``TFCluster.train`` but partitions are
        acked as consumed, failures classify and recover per the policy,
        and the final (clean) shutdown happens inside — a successful
        ``train`` leaves nothing running. Raises when the policy gives
        up; ``report()`` carries the full timeline either way."""
        InputMode = self._cluster_mod.InputMode
        assert self.input_mode == InputMode.SPARK, \
            "supervised train() requires InputMode.SPARK"
        if hasattr(dataRDD, "foreachRDD"):
            raise NotImplementedError(
                "supervised streaming training is not supported; use the "
                "unsupervised cluster for DStreams")
        if num_epochs > 1:
            dataRDD = self.sc.union([dataRDD] * num_epochs)
        # the ack ledger is per-train(): partition ordinals are indices
        # into THIS dataRDD, and a second train() on a fresh dataset
        # must not inherit the first one's acks (it would silently drain
        # every colliding partition unfed — total data loss dressed up
        # as success)
        self._acked = set()
        self.events.record("train_start",
                           partitions=dataRDD.getNumPartitions())
        while True:
            if self._tfc is None:
                try:
                    self._form()
                except Exception as e:  # noqa: BLE001 - policy decides
                    self._recover_or_raise(
                        FailureEvent("reform_failed", None, str(e)))
                    continue
            failure = self._run_feed_attempt(dataRDD, feed_timeout, qname)
            if failure is None:
                failure = self._final_shutdown()
                if failure is None:
                    self._done = True
                    self._freeze_goodput_wall()
                    self._resize_target = None  # drain raced completion
                    self.events.record("job_complete",
                                       formations=self.formations)
                    return
            if self._resize_target is not None:
                if failure.kind in ("executor_lost", "feeder_stall",
                                    "ring_wedge", "reform_failed"):
                    # a REAL failure landed inside the drain window —
                    # kinds the drain itself can never produce (its
                    # trainers exit with code 1, classifying as
                    # trainer_crash/task_failure). The planned resize
                    # is moot: capacity just changed under it, so the
                    # policy must decide with the failure on the books
                    self._resize_target = None
                    self._recover_or_raise(failure)
                    continue
                # planned boundary drain (elastic regrow), not a real
                # failure: the trainers exited via ResizeDrain at their
                # checkpoint boundary — reform at the target width
                # without consulting the policy or advancing
                # failure_counts. (A genuine trainer crash racing the
                # drain is indistinguishable from the drain's own exit
                # and rides this path too — bounded at one uncounted
                # reform per regrow, and the reformed attempt's own
                # failures count normally.)
                self._complete_resize(failure)
                continue
            self._recover_or_raise(failure)

    def inference(self, dataRDD, feed_timeout=600, qname="output"):
        raise NotImplementedError(
            "supervised inference is not implemented: the result-RDD "
            "contract (exactly one output row per input record) has no "
            "replay story yet; run inference unsupervised")

    def shutdown(self, ssc=None, grace_secs=0, timeout=None):
        """SPARK mode: finalize (train() already supervised the work).
        TENSORFLOW mode: the supervised attempt loop lives HERE — each
        attempt awaits the inline map_fun job and a failure reforms the
        cluster so the resubmitted map_fun restores from its checkpoint.
        Returns :meth:`report`."""
        if ssc is not None:
            raise NotImplementedError(
                "supervised streaming shutdown is not supported")
        InputMode = self._cluster_mod.InputMode
        while not self._done:
            if self._tfc is None:
                try:
                    self._form()
                except Exception as e:  # noqa: BLE001 - policy decides
                    self._recover_or_raise(
                        FailureEvent("reform_failed", None, str(e)))
                    continue
            if self.input_mode == InputMode.TENSORFLOW:
                failure = self._await_result(self._tfc.async_result)
                if failure is None:
                    failure = self._final_shutdown(grace_secs=grace_secs)
            else:
                failure = self._final_shutdown(grace_secs=grace_secs)
            if failure is None:
                self._done = True
                self._freeze_goodput_wall()
                self.events.record("job_complete",
                                   formations=self.formations)
                break
            self._recover_or_raise(failure)
        return self.report()

    def report(self):
        """The supervision ledger: formations, failures, exclusions,
        ack coverage, MTTR stages, goodput accounting, observe-only
        incidents, and the raw event timeline."""
        return {
            "formations": self.formations,
            "failures": [a["failure"] for a in self.attempts],
            "width": self.width,
            "width_changes": [
                {k: e[k] for k in ("from_width", "to_width", "reason")}
                for e in self.events.events("width_change")],
            "excluded": sorted(self.excluded),
            "acked_partitions": len(self._acked),
            "recovery": recovery_stages(self.events),
            "goodput": self.goodput_report(),
            "incidents": list(self.incidents),
            "events": self.events.events(),
        }

    def goodput_report(self):
        """Job-level goodput accounting (goodput.job_report): the
        driver ledger's recovery windows folded with every attempt's
        merged executor categories, against this job's wall clock.
        Executor seconds are normalized by the configured width, so
        ``goodput_ratio`` reads in job wall-clock units (1.0 == every
        executor productive for the whole wall time); elastic attempts
        running below the configured width under-count proportionally
        — honest for a degraded job. ``scripts/goodput_report.py``
        renders this; ``bench.py``'s goodput leg publishes it."""
        self._harvest_metrics()
        merged = []
        for ordinal in sorted(self._attempt_rollups):
            rollup = self._attempt_rollups[ordinal] or {}
            snap = (rollup.get("cluster") or {}).get("merged")
            if snap:
                merged.append(snap)
        # the wall denominator FREEZES when the job completes or fails
        # — a report read minutes after shutdown must describe the job,
        # not dilute its ratio with post-job elapsed time as idle
        wall = self._goodput_wall_s if self._goodput_wall_s is not None \
            else self.goodput.wall_s()
        return goodput_mod.job_report(
            wall, driver_ledger=self.goodput,
            merged_snapshots=merged, width=self.num_executors)

    def _freeze_goodput_wall(self):
        if self._goodput_wall_s is None:
            self._goodput_wall_s = self.goodput.wall_s()

    # -- attempt machinery -----------------------------------------------

    def _form(self):
        width = self.width
        attempt_no = len(self.attempts) + 1
        self.events.record("reform_start", attempt=attempt_no, width=width)
        # the formation window is recovery badput: "reform" normally,
        # "resize_drain" when this formation completes a planned
        # boundary drain (elastic regrow). The job's FIRST formation is
        # startup, not recovery — the taxonomy's reform means the
        # window BETWEEN attempts, and a clean zero-failure job must
        # report reform 0 — so it stays uncharged (it lands in the
        # report's idle residual)
        category, self._next_form_category = \
            self._next_form_category, "reform"
        if self.formations == 0 and category == "reform":
            category = None
        with self.goodput.track(category) if category \
                else contextlib.nullcontext():
            tfc = self._cluster_mod.run(
                self.sc, self.map_fun, self.tf_args, width,
                exclude_executors=frozenset(self.excluded),
                beat_interval=self.config.heartbeat_interval,
                prefer_alive=True,
                **self.run_kwargs)
        self.formations += 1
        self._tfc = tfc
        # width gauge: this formation's width against the job's
        # CONFIGURED width — width < target on /metrics is the
        # operator's "running degraded after a shrink" signal
        tfc.server.set_cluster_width(width, target=self.num_executors)
        self._supervisor = Supervisor(
            server=tfc.server, executors=tfc.executor_ids,
            config=self.config, events=self.events,
            attempt=attempt_no, incidents=self.incidents,
            alive_fn=getattr(self.sc, "executors_alive", None)).start()
        self.events.record("cluster_formed", attempt=attempt_no,
                           width=width, executors=list(tfc.executor_ids))

    def _run_feed_attempt(self, dataRDD, feed_timeout, qname):
        tfc = self._tfc
        mapped = dataRDD.mapPartitionsWithIndex(acked_feed(
            tfc.cluster_info, tfc.cluster_meta, frozenset(self._acked),
            feed_timeout=feed_timeout, qname=qname))
        # feed tasks may only run on executors HOSTING this formation's
        # nodes: after an elastic shrink (or mid-attempt regrow of
        # capacity) the engine can have alive executors that are not
        # cluster members, and a feed task landing there has no node to
        # feed. Blacklist exclusions fold into the same set.
        exclude = set(tfc.exclude)
        members = set(tfc.executor_ids)
        universe = set(range(self.num_executors)) | \
            set(self._capacity() or ())
        exclude |= universe - members
        kwargs = {"exclude": frozenset(exclude)} if exclude else {}
        result = mapped.foreachPartitionAsync(_drain_iter, **kwargs)
        failure = self._await_result(result, probe=self._regrow_probe)
        # harvest acks even on failure: the next attempt must not replay
        # what this one's trainers already consumed
        self._acked |= tfc.server.acked_partitions()
        return failure

    def _await_result(self, result, probe=None):
        """Poll a job result against the monitor; None on success, else
        the classified FailureEvent. A monitor-detected failure aborts
        the attempt remotely first so blocked tasks unwind. ``probe``
        (the elastic regrow capacity watch) runs once per poll."""
        sup = self._supervisor
        while True:
            if probe is not None:
                try:
                    probe()
                except Exception:  # noqa: BLE001 - probe is best-effort
                    logger.debug("regrow probe failed", exc_info=True)
            failure = sup.first_failure()
            if failure is not None:
                # monitor OFF before the remote abort: the abort flips
                # every node's state to 'error', and a still-polling
                # monitor would attribute those self-inflicted errors to
                # healthy executors — poisoning failure_counts, which
                # Blacklist decides exclusions from. The whole
                # abort+drain window is recovery badput (goodput
                # plane): the _recover_or_raise that follows continues
                # the same reform charge
                with self.goodput.track("reform"):
                    sup.stop()
                    sup.abort_attempt(self._tfc.cluster_info,
                                      self._tfc.cluster_meta,
                                      str(failure))
                    self._drain_result(result)
                return failure
            err = result.first_error()
            if err is not None:
                # task error beat the monitor: give classification one
                # grace window to attribute it to a lease
                failure = sup.wait_for_failure(self.config.classify_grace)
                # drain in-flight tasks BEFORE returning: a feed task
                # that consumed its partition may be one reply away
                # from completing — its ACK must land before the
                # caller harvests acked_partitions(), or the partition
                # replays against state that already contains it
                self._drain_result(result)
                return failure if failure is not None else FailureEvent(
                    "task_failure", None, str(err))
            if result.done():
                return None
            time.sleep(self.config.poll_interval)

    def _drain_result(self, result, timeout=None):
        deadline = time.monotonic() + (timeout or self.config.drain_timeout)
        while not result.done() and time.monotonic() < deadline:
            time.sleep(0.1)

    # -- elastic resize (regrow) -----------------------------------------

    def _elastic_policy(self):
        """The configured policy when it carries the elastic knobs
        (duck-typed: min_width/max_width/regrow_probe_s), else None."""
        policy = self.config.policy
        if all(hasattr(policy, a) for a in
               ("min_width", "max_width", "regrow_probe_s",
                "shrink_grace_s")):
            return policy
        return None

    def _capacity(self):
        """Alive, non-excluded engine executors (None without the
        engine's liveness view — Spark contexts cannot regrow)."""
        alive_fn = getattr(self.sc, "executors_alive", None)
        if alive_fn is None:
            return None
        try:
            return [e for e in alive_fn() if e not in self.excluded]
        except Exception:  # noqa: BLE001 - liveness view is best-effort
            return None

    def _regrow_probe(self):
        """Capacity watch, run from the attempt poll loop: when the
        job runs below its elastic max width and spare executors
        exist, request a boundary drain so the next checkpoint
        boundary reforms UP. One shot per attempt (the drain itself
        ends the attempt)."""
        policy = self._elastic_policy()
        if policy is None or self._resize_target is not None \
                or self._tfc is None:
            return
        now = time.monotonic()
        if now - self._last_probe < policy.regrow_probe_s:
            return
        self._last_probe = now
        max_width = policy.max_width if policy.max_width is not None \
            else self.num_executors
        if self.width >= max_width:
            return
        capacity = self._capacity()
        if capacity is None or len(capacity) <= self.width:
            return
        target = min(len(capacity), max_width)
        self._resize_target = target
        self.events.record("regrow_requested", width=self.width,
                           target=target, capacity=len(capacity))
        logger.warning("elastic regrow: capacity %d > width %d; "
                       "requesting boundary drain to reform at %d",
                       len(capacity), self.width, target)
        self._request_resize_drain(target)

    def _request_resize_drain(self, target):
        """Set every node's broker ``resize_drain`` key so each trainer
        exits via :class:`ResizeDrain` at its next step boundary
        (checkpoint committed, partition acked). Best effort per node —
        the analog of :meth:`Supervisor.abort_attempt`, but cooperative
        and boundary-aligned instead of immediate."""
        import multiprocessing

        from tensorflowonspark_tpu import manager
        tfc = self._tfc
        if tfc is None:
            return
        authkey = bytes.fromhex(tfc.cluster_meta["authkey"])
        multiprocessing.current_process().authkey = authkey
        for node_meta in tfc.cluster_info:
            try:
                mgr = manager.connect(tuple(node_meta["mgr_addr"]), authkey)
                mgr.set("resize_drain", int(target))
            except Exception:  # noqa: BLE001 - node may be gone
                logger.debug("resize drain could not reach executor %s",
                             node_meta.get("executor_id"), exc_info=True)

    def _complete_resize(self, failure):
        """Finish a PLANNED resize: tear the drained attempt down and
        move width to the target — no policy consult, no
        failure_counts (the 'failure' here is the drain's own exit
        surfacing through the normal channels)."""
        target, self._resize_target = self._resize_target, None
        attempt_no = len(self.attempts) + 1
        self.events.record("attempt_teardown", attempt=attempt_no,
                           kind="resize_drain", surfaced=failure.kind)
        with self.goodput.track("resize_drain"):
            self._teardown(
                "resize drain (regrow to width {})".format(target),
                attempt_no=attempt_no)
        # the formation that completes the resize is part of its cost
        self._next_form_category = "resize_drain"
        self._record_width_change(target, "regrow: capacity returned")
        # the next loop iteration reforms at the new width

    def _record_width_change(self, new_width, reason):
        if new_width == self.width:
            return
        self.events.record("width_change", from_width=self.width,
                           to_width=new_width, reason=reason)
        logger.warning("cluster width %d -> %d (%s)", self.width,
                       new_width, reason)
        self.width = int(new_width)

    def _harvest_metrics(self):
        """Snapshot the live cluster's metrics rollup before a teardown
        discards it — a completed (or failed) supervised job must still
        be able to report what its executors measured. Reads ``_tfc``
        ONCE (a concurrent teardown may null it between check and use)
        and treats any failure as best-effort."""
        tfc = self._tfc
        if tfc is None:
            return
        try:
            rollup = tfc.metrics()
            self._last_metrics = rollup
            # per-ATTEMPT accumulation (goodput plane): each attempt's
            # trainers run fresh process ledgers, so the job's total
            # accounting is the SUM of attempts' merged snapshots;
            # within one attempt the counters are cumulative, so
            # overwriting by formation ordinal keeps only the latest
            # harvest of each attempt — unless the new harvest is
            # EMPTY (a final beat whose broker was already gone
            # carries metrics=None): never regress a rollup that has
            # data to one that lost it
            merged = (rollup.get("cluster") or {}).get("merged") or {}
            if any(merged.get(k) for k in ("counters", "timers",
                                           "hists")) \
                    or self.formations not in self._attempt_rollups:
                self._attempt_rollups[self.formations] = rollup
        except Exception:  # noqa: BLE001 - observability is best-effort
            logger.debug("metrics harvest failed", exc_info=True)

    def _final_shutdown(self, grace_secs=0):
        """Shut the live cluster down cleanly; None on success, else the
        failure it surfaced (monitor-attributed when possible)."""
        self._harvest_metrics()
        tfc, sup = self._tfc, self._supervisor
        try:
            tfc.shutdown(grace_secs=grace_secs,
                         timeout=self.config.shutdown_timeout)
        except Exception as e:  # noqa: BLE001 - classified below
            # A shutdown-surfaced error is usually the monitor's failure
            # seen through a different channel (a trainer killed so fast
            # its node drained the whole feed as error-state no-ops, so
            # the job "completed" before a beat carried the crash): give
            # classification one grace window to attribute it to a lease
            # — the exact analog of the task-error path in _await_result.
            # An unattributed shutdown_failure carries no executor_id and
            # can never advance Blacklist's failure_counts.
            failure = sup.wait_for_failure(self.config.classify_grace) \
                if sup is not None else None
            self._stop_monitor()
            self._tfc = None
            return failure if failure is not None else FailureEvent(
                "shutdown_failure", None, str(e))
        # re-harvest AFTER the shutdown join (goodput plane): the
        # trainers' FINAL accounting flush rides their last synchronous
        # beat, which only lands once node.shutdown has joined them —
        # the pre-shutdown harvest above would miss the last steps'
        # charges to the publish-throttle window. The lease payloads
        # stay readable in memory after Server.stop(); a failed
        # re-harvest keeps the earlier one (best-effort either way).
        self._harvest_metrics()
        self._stop_monitor()
        self._tfc = None
        return None

    def _stop_monitor(self):
        if self._supervisor is not None:
            self._supervisor.stop()

    def _teardown_attempt(self, attempt_no, failure):
        self.events.record("attempt_teardown", attempt=attempt_no,
                           kind=failure.kind)
        self._teardown(str(failure), attempt_no=attempt_no)

    def _teardown(self, reason, attempt_no=None):
        """Tear the live attempt down after a failure or planned drain:
        abort surviving nodes FIRST (their trainers may still be
        consuming — an executor loss ends the feed job without ever
        delivering EndFeed to the survivors, and a shutdown join with a
        dead executor raises before dispatching), then best-effort
        shutdown."""
        self._harvest_metrics()
        sup = self._supervisor
        self._stop_monitor()
        tfc, self._tfc = self._tfc, None
        if tfc is None:
            return
        try:
            (sup or Supervisor()).abort_attempt(
                tfc.cluster_info, tfc.cluster_meta, reason)
        except Exception:  # noqa: BLE001 - nodes may all be gone
            logger.debug("attempt abort failed", exc_info=True)
        try:
            tfc.shutdown(grace_secs=1,
                         timeout=self.config.shutdown_timeout)
        except Exception as e:  # noqa: BLE001 - this IS the failure
            logger.info("attempt %s teardown surfaced: %s",
                        attempt_no if attempt_no is not None else "?", e)

    def _recover_or_raise(self, failure):
        # the whole recovery window — teardown, decision, backoff —
        # is reform badput on the driver ledger (the next _form adds
        # the formation itself); the context closes on the FAIL raise
        # path too
        with self.goodput.track("reform"):
            self._recover_or_raise_inner(failure)

    def _recover_or_raise_inner(self, failure):
        attempt_no = len(self.attempts) + 1
        restarts = len(self.attempts)  # restarts already performed
        self.attempts.append({"attempt": attempt_no,
                              "failure": failure.as_dict()})
        if failure.executor_id is not None:
            self.failure_counts[failure.executor_id] = \
                self.failure_counts.get(failure.executor_id, 0) + 1
        self._teardown_attempt(attempt_no, failure)
        decision = self._decide(failure, restarts)
        self.events.record("decision", attempt=attempt_no,
                           action=decision.action, delay=decision.delay,
                           exclude=sorted(decision.exclude),
                           width=decision.width,
                           reason=decision.reason)
        if decision.action == Decision.FAIL:
            self._done = True
            self._freeze_goodput_wall()
            self.events.record("job_failed", attempt=attempt_no,
                               kind=failure.kind)
            raise RuntimeError(
                "supervised job failed after {} attempt(s) — {} ({})".format(
                    attempt_no, failure, decision.reason))
        if decision.action == Decision.RESIZE:
            self._apply_shrink(decision)
        if decision.exclude:
            self.excluded |= set(decision.exclude)
            self.events.record("blacklisted",
                               executors=sorted(decision.exclude))
            # blacklist and resize share ONE width source of truth
            self._record_width_change(
                self.num_executors - len(self.excluded),
                "blacklist: excluded {}".format(sorted(decision.exclude)))
        if decision.delay:
            logger.info("supervisor backing off %.1fs before restart",
                        decision.delay)
            time.sleep(decision.delay)
        # the next loop iteration (train) or shutdown pass reforms

    def _decide(self, failure, restarts):
        """Consult the policy, passing the current width only to
        policies that take it — user-defined policies implementing the
        pre-elastic 5-argument ``decide`` signature keep working."""
        import inspect
        policy = self.config.policy
        kwargs = {}
        try:
            params = inspect.signature(policy.decide).parameters
            if "width" in params or any(
                    p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in params.values()):
                kwargs["width"] = self.width
        except (TypeError, ValueError):  # unintrospectable callable
            pass
        return policy.decide(
            failure, restarts, dict(self.failure_counts),
            frozenset(self.excluded), self.num_executors, **kwargs)

    def _apply_shrink(self, decision):
        """Commit (or cancel) a RESIZE decision: hold for the policy's
        shrink grace first — a flapping executor that returns within it
        keeps the original width (reform, not resize)."""
        grace = getattr(self.config.policy, "shrink_grace_s", 0.0)

        def _capacity_back():
            capacity = self._capacity()
            return capacity is not None and len(capacity) >= self.width

        deadline = time.monotonic() + max(0.0, grace)
        returned = _capacity_back()
        while not returned and time.monotonic() < deadline:
            time.sleep(0.05)
            returned = _capacity_back()
        if returned:
            self.events.record("shrink_cancelled", width=self.width,
                               reason="capacity available within "
                                      "shrink grace")
            logger.warning("shrink to %s cancelled: capacity for width "
                           "%d is available", decision.width, self.width)
            return
        self._record_width_change(decision.width, decision.reason)
