"""Driver for ``make racecheck``: run the three concurrency passes
over a file tree, apply inline suppressions and the checked-in
baseline, and render the verdict through the shared report helper.

Workflow (docs/static_analysis.md has the long form):

- a NEW finding fails the build. Fix it, or — if it is provably
  benign (single-writer by construction, join-by-interpreter-exit,
  ...) — either suppress it inline::

      self._steps += 1  # tfos: unguarded(scheduler thread is the only writer)

  or add its ``key`` to ``analysis/baseline.json`` with a written
  ``reason``. Both demand the reason: an empty suppression reason is
  itself a finding, and a baseline entry without one fails the gate.
- a STALE baseline entry (the finding it matched is gone) is a
  warning: prune it with the fix that removed it.

Exit codes: 0 clean (modulo baseline), 1 findings, 2 usage/IO errors.
Stdlib only (``ast`` + ``json``); the whole package scans in well
under a second, so the gate is free as a ``make test`` prerequisite.
"""

import argparse
import ast
import json
import os
import sys

from tensorflowonspark_tpu.analysis import core, guards, lifecycle, \
    lockorder, report

#: finding rule -> the suppression tag that silences it
SUPPRESS_TAGS = {
    "unguarded": "unguarded",
    "cross-thread": "unguarded",
    "lock-order": "lock-order",
    "lock-self-nest": "lock-order",
    "thread-daemon": "daemon",
    "thread-name": "daemon",
    "thread-unjoined": "unjoined",
    "retriable-swallow": "swallow",
}

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def analyze_file(path, rel=None):
    """(findings, suppressed_count, bad_suppression_findings) for one
    file. ``rel`` overrides the path recorded on findings (the
    repo-relative form the baseline keys on)."""
    rel = rel if rel is not None else path
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    marks = core.scan_suppressions(source)
    models = core.build_class_models(tree, rel)
    found = []
    found.extend(guards.check(models))
    found.extend(lockorder.check(models))
    found.extend(lifecycle.check(tree, rel))
    kept, suppressed, bad = [], 0, []
    for f in found:
        tag = SUPPRESS_TAGS.get(f.rule, f.rule)
        hit = None
        # a suppression counts on ANY of the finding's site lines (or
        # the line above each) — multi-site findings like cross-thread
        # accept it at whichever site the author annotates
        for site in f.lines:
            for line in (site, site - 1):
                for mtag, reason in marks.get(line, ()):
                    if mtag == tag:
                        hit = (line, reason)
        if hit is None:
            kept.append(f)
        elif not hit[1]:
            bad.append(report.Finding(
                "bad-suppression", rel, hit[0], f.ident,
                "suppression '# tfos: {}(...)' has an EMPTY reason — "
                "the grammar demands one (suppressing: {})".format(
                    tag, f.key)))
        else:
            suppressed += 1
    return kept, suppressed, bad


def load_baseline(path):
    """{key: reason} plus a list of malformed-entry findings."""
    with open(path) as f:
        doc = json.load(f)
    entries, bad = {}, []
    for entry in doc.get("entries", []):
        key = entry.get("key")
        reason = (entry.get("reason") or "").strip()
        if not key:
            continue
        if not reason:
            bad.append(report.Finding(
                "baseline-missing-reason", os.path.basename(path), 0,
                key, "baseline entry has no written reason: "
                "{}".format(key)))
        entries[key] = reason
    return entries, bad


def run(paths, baseline_path, emit_skeleton=False,
        out=sys.stdout, err=sys.stderr):
    findings, grammar_bad, baseline_bad = [], [], []
    suppressed = files = 0
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, os.path.dirname(_PKG_ROOT)) \
            if os.path.isabs(path) else path
        files += 1
        kept, nsup, bad = analyze_file(path, rel=rel)
        findings.extend(kept)
        grammar_bad.extend(bad)
        suppressed += nsup
    baselined = 0
    stale = ()
    if baseline_path:
        try:
            entries, baseline_bad = load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print("racecheck: cannot read baseline {}: {}".format(
                baseline_path, e), file=err)
            return 2
        matched = {f.key for f in findings if f.key in entries}
        baselined = len([f for f in findings if f.key in entries])
        findings = [f for f in findings if f.key not in entries]
        stale = sorted(set(entries) - matched)
    # grammar violations (empty-reason suppressions) and malformed
    # baseline entries join AFTER the baseline filter: the
    # mandatory-reason rule must not itself be baselineable away
    findings.extend(grammar_bad)
    findings.extend(baseline_bad)
    if emit_skeleton:
        # grammar violations are not baselineable — fix the comment /
        # the entry, don't launder it through the skeleton
        baselineable = sorted(
            {f.key for f in findings
             if f.rule not in ("bad-suppression",
                               "baseline-missing-reason")})
        json.dump({"entries": [
            {"key": key, "reason": ""} for key in baselineable]},
            out, indent=2)
        out.write("\n")
        return 1 if findings else 0
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report.emit(
        "racecheck", findings,
        ok_summary="{} file(s), {} finding(s) suppressed inline, {} "
                   "baselined, 0 new".format(files, suppressed,
                                             baselined),
        stale=stale, out=out, err=err)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="racecheck",
        description="Concurrency lint: guarded-attribute races, "
                    "lock-order cycles, thread-lifecycle rules.")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to scan (default: the installed "
             "tensorflowonspark_tpu package)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline JSON ('none' disables; default: the package's "
             "analysis/baseline.json when scanning the package, none "
             "for explicit paths)")
    parser.add_argument(
        "--emit-baseline", action="store_true",
        help="print a baseline-entry skeleton for the current NEW "
             "findings (reasons left empty — write them before "
             "committing)")
    args = parser.parse_args(argv)
    paths = args.paths or [_PKG_ROOT]
    if args.baseline is None:
        # only the IMPLICIT default may quietly not exist (a fresh
        # checkout before any baseline is written); an explicit
        # --baseline path that is missing is an IO error below — a CI
        # whose baseline file moved must fail loudly, not silently
        # lint baseline-less (use `--baseline none` to disable)
        baseline = DEFAULT_BASELINE if not args.paths else None
        if baseline and not os.path.exists(baseline):
            baseline = None
    elif args.baseline == "none":
        baseline = None
    else:
        baseline = args.baseline
    return run(paths, baseline, emit_skeleton=args.emit_baseline)


if __name__ == "__main__":
    sys.exit(main())
