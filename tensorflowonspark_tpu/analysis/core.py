"""Shared AST plumbing for the concurrency passes.

Builds a per-class model — which attributes hold locks, which
synchronization primitives are exempt, every attribute MUTATION with
the set of locks held at that statement, every intra-class call site
with its lock context, every ``Thread(target=...)`` root — that
:mod:`guards` and :mod:`lockorder` analyze. Everything is syntactic
and intra-class by design: the codebase's locking discipline is
per-object (``self._lock`` guards ``self.*``), and the passes only
claim what the AST can prove, with the suppression grammar and the
baseline absorbing the judgement calls.

Lock-context tracking is ``with``-statement based (the package has no
manual ``.acquire()`` call sites — verified, and simpler to keep it
that way than to approximate flow-sensitivity). A
``threading.Condition(self._lock)`` ALIASES its underlying lock:
holding the condition holds the lock, which both the guard pass (a
``_cv`` block guards ``_lock``-guarded attrs) and the lock-order pass
(entering ``_cv`` while holding ``_lock`` is a self-acquisition of a
non-reentrant lock) need to know.

Nested functions (closures) are scanned with an EMPTY lock context:
a closure's body runs when it is called — often on another thread
entirely (``Thread(target=closure)``) — and the locks held at its
*definition* site prove nothing about its *call* sites. A closure
invoked inline under the lock is the false-positive shape the
``# tfos: unguarded(...)`` suppression exists for.
"""

import ast
import re

#: threading factories whose product is a lock for guard purposes
LOCK_FACTORIES = ("Lock", "RLock", "Condition")

#: factories whose product is internally synchronized — attributes
#: holding one are exempt from mutation analysis (calling
#: ``self._stop.clear()`` on an Event is not a data race)
SYNC_FACTORIES = LOCK_FACTORIES + (
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "Queue",
    "LifoQueue", "PriorityQueue", "SimpleQueue", "local")

#: method names that mutate their receiver in place — a call
#: ``self.X.append(...)`` is a mutation of ``X`` exactly as
#: ``self.X = ...`` is (dict/list/set/OrderedDict/deque vocabulary)
MUTATOR_METHODS = frozenset((
    "append", "extend", "insert", "remove", "clear", "update",
    "setdefault", "add", "discard", "pop", "popitem", "appendleft",
    "extendleft", "popleft", "move_to_end", "rotate", "sort",
    "reverse"))

#: the inline suppression grammar: ``# tfos: <tag>(<reason>)`` — one
#: per line, reason runs to the line's LAST closing paren (so reasons
#: may themselves mention ``stop()`` and friends)
SUPPRESS_RE = re.compile(
    r"#\s*tfos:\s*([a-z][a-z-]*)\((.*)\)\s*$")


def scan_suppressions(source):
    """{lineno: [(tag, reason), ...]} for every ``# tfos: tag(...)``
    comment in ``source`` (1-based line numbers, matching ast)."""
    out = {}
    for i, line in enumerate(source.splitlines(), start=1):
        hits = SUPPRESS_RE.findall(line)
        if hits:
            out[i] = [(tag, reason.strip()) for tag, reason in hits]
    return out


def call_name(node):
    """Trailing name of a Call's callee (``threading.Thread`` ->
    ``Thread``; ``Thread`` -> ``Thread``), else None."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def self_attr(node):
    """``attr`` when ``node`` is exactly ``self.<attr>``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutated_attr(target):
    """The ``self`` attribute a bind target mutates: ``self.X`` and
    ``self.X[...]`` both mutate ``X``; anything deeper
    (``self.a.b = v`` mutates another object) is out of scope."""
    if isinstance(target, ast.Subscript):
        return self_attr(target.value)
    return self_attr(target)


class Mutation(object):
    """One attribute mutation site: ``attr`` mutated at ``line`` with
    ``locks`` (frozenset of lock-attribute names) held, in the method
    whose record owns this. ``kind`` is assign/augassign/delete/call;
    ``nested`` names the enclosing closure (None for method-body
    statements) — closures that are Thread targets root their
    mutations on that thread."""

    __slots__ = ("attr", "line", "locks", "kind", "nested")

    def __init__(self, attr, line, locks, kind, nested=None):
        self.attr = attr
        self.line = line
        self.locks = locks
        self.kind = kind
        self.nested = nested


class CallSite(object):
    """Intra-class call ``self.<callee>(...)`` at ``line`` with
    ``locks`` held (``nested`` as in :class:`Mutation`)."""

    __slots__ = ("callee", "line", "locks", "nested")

    def __init__(self, callee, line, locks, nested=None):
        self.callee = callee
        self.line = line
        self.locks = locks
        self.nested = nested


class MethodModel(object):
    def __init__(self, name, node):
        self.name = name
        self.node = node
        self.mutations = []      # [Mutation]
        self.calls = []          # [CallSite]
        self.acquires = set()    # lock attrs acquired by with stmts
        self.with_edges = []     # [(outer_lock, inner_lock, line)]
        #: nested function names used as Thread targets in this method
        self.thread_nested = set()
        #: attributes on which ``.join(`` is called anywhere in here
        self.joined_attrs = set()

    @property
    def is_private(self):
        return self.name.startswith("_") and not self.name.startswith("__")

    @property
    def is_dunder(self):
        return self.name.startswith("__") and self.name.endswith("__")


class ClassModel(object):
    """Everything the passes need to know about one class."""

    def __init__(self, name, path):
        self.name = name
        self.path = path
        self.locks = {}        # lock attr -> factory name
        self.cv_alias = {}     # condition attr -> wrapped lock attr
        self.sync_attrs = set()
        self.methods = {}      # name -> MethodModel
        #: bound methods used as Thread targets anywhere in the class
        #: (``Thread(target=self._loop)``)
        self.thread_targets = set()

    def expand(self, locks):
        """Lock set closed over condition aliases: holding a
        ``Condition(self._lock)`` holds ``_lock`` too."""
        out = set(locks)
        for cv in locks:
            alias = self.cv_alias.get(cv)
            if alias is not None:
                out.add(alias)
        return frozenset(out)


def _thread_target_of(call):
    """(kind, name) for a ``Thread(...)``/``Timer(...)`` call's
    entry callable: ("method", attr) for ``target=self.X`` (Timer:
    the positional ``function`` or ``function=`` kwarg), ("local",
    name) for a local/closure callable, else (None, None)."""
    name = call_name(call)
    if name not in ("Thread", "Timer"):
        return None, None
    candidates = [kw.value for kw in call.keywords
                  if kw.arg in ("target", "function")]
    if name == "Timer" and len(call.args) >= 2:
        candidates.append(call.args[1])
    for value in candidates:
        attr = self_attr(value)
        if attr is not None:
            return "method", attr
        if isinstance(value, ast.Name):
            return "local", value.id
    return None, None


class _MethodScanner(object):
    """Walks one method body tracking the set of locks held at each
    statement (``with self._lock:`` pushes; leaving the block pops),
    recording mutations, intra-class calls, acquisition edges, and
    thread-target registrations into the method/class models."""

    def __init__(self, cls, method):
        self.cls = cls
        self.method = method

    def scan(self):
        for stmt in self.method.node.body:
            self._visit(stmt, frozenset(), None)

    # -- helpers ---------------------------------------------------------

    def _record_mutation(self, attr, line, held, kind, nested):
        if attr is None or attr in self.cls.sync_attrs \
                or attr in self.cls.locks:
            return
        self.method.mutations.append(
            Mutation(attr, line, held, kind, nested))

    def _visit_call(self, node, held, nested):
        # thread-target registration (Thread(target=self._loop) makes
        # _loop a thread root; Thread(target=closure) roots the
        # closure's mutations on that thread)
        kind, name = _thread_target_of(node)
        if kind == "method":
            self.cls.thread_targets.add(name)
        elif kind == "local":
            self.method.thread_nested.add(name)
        # mutator-method calls: self.X.append(...) mutates X
        func = node.func
        if isinstance(func, ast.Attribute):
            owner = self_attr(func.value)
            if owner is not None and func.attr in MUTATOR_METHODS:
                self._record_mutation(owner, node.lineno, held, "call",
                                      nested)
            if owner is not None and func.attr == "join":
                self.method.joined_attrs.add(owner)
            # intra-class call: self._helper(...)
            callee = self_attr(func)
            if callee is not None:
                self.method.calls.append(
                    CallSite(callee, node.lineno, held, nested))

    # -- the walk --------------------------------------------------------

    def _visit(self, node, held, nested):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                lock = self_attr(item.context_expr)
                if lock is not None and lock in self.cls.locks:
                    acquired.add(lock)
                else:
                    self._visit(item.context_expr, held, nested)
            if acquired:
                inner = self.cls.expand(acquired)
                for outer_lock in self.cls.expand(held):
                    for lock in inner:
                        self.method.with_edges.append(
                            (outer_lock, lock, node.lineno))
                held = frozenset(held | inner)
                self.method.acquires.update(inner)
            for stmt in node.body:
                self._visit(stmt, held, nested)
            return
        if isinstance(node, ast.Assign):
            self._scan_assign_value(node, held, nested)
            for target in node.targets:
                self._bind_target(target, node.lineno, held, nested)
            self._visit(node.value, held, nested)
            return
        if isinstance(node, ast.AugAssign):
            self._record_mutation(_mutated_attr(node.target),
                                  node.lineno, held, "augassign", nested)
            self._visit(node.value, held, nested)
            return
        if isinstance(node, ast.Delete):
            for target in node.targets:
                self._record_mutation(_mutated_attr(target),
                                      node.lineno, held, "delete", nested)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, held, nested)
            for child in ast.iter_child_nodes(node):
                self._visit(child, held, nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closure body: lock context at the DEFINITION site proves
            # nothing about the call site (often another thread)
            for stmt in node.body:
                self._visit(stmt, frozenset(), node.name)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, frozenset(), nested)
            return
        if isinstance(node, ast.ClassDef):
            return  # nested classes are modeled separately
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, nested)

    def _bind_target(self, target, line, held, nested):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, line, held, nested)
            return
        self._record_mutation(_mutated_attr(target), line, held,
                              "assign", nested)
        # subscript targets carry expressions of their own
        # (self._x[self._key()] = v) that still need the walk
        for child in ast.iter_child_nodes(target):
            self._visit(child, held, nested)

    def _scan_assign_value(self, node, held, nested):
        """Factory detection on ``self.X = <Call>`` assignments: lock
        attrs, condition aliases, and sync-primitive exemptions."""
        if not isinstance(node.value, ast.Call):
            return
        name = call_name(node.value)
        targets = [self_attr(t) for t in node.targets]
        targets = [t for t in targets if t is not None]
        if not targets or name is None:
            return
        if name in LOCK_FACTORIES:
            for t in targets:
                self.cls.locks[t] = name
                self.cls.sync_attrs.add(t)
            if name == "Condition" and node.value.args:
                wrapped = self_attr(node.value.args[0])
                if wrapped is not None:
                    for t in targets:
                        self.cls.cv_alias[t] = wrapped
        elif name in SYNC_FACTORIES:
            for t in targets:
                self.cls.sync_attrs.add(t)


def build_class_models(tree, path):
    """[:class:`ClassModel`] for every class in ``tree`` (module AST).

    Two phases per class: first collect lock/sync attribute
    declarations from EVERY method (a lock declared in ``__init__``
    guards mutations in methods defined above it in the source), then
    scan method bodies with the full declaration picture."""
    models = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = ClassModel(node.name, path)
        method_nodes = [
            n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for mnode in method_nodes:
            cls.methods[mnode.name] = MethodModel(mnode.name, mnode)
        # phase 1: factory declarations (self.X = threading.Lock()...)
        for mnode in method_nodes:
            method = cls.methods[mnode.name]
            for sub in ast.walk(mnode):
                if isinstance(sub, ast.Assign):
                    _MethodScanner(cls, method)._scan_assign_value(
                        sub, frozenset(), None)
        # phase 2: the lock-context walk proper
        for mnode in method_nodes:
            _MethodScanner(cls, cls.methods[mnode.name]).scan()
        models.append(cls)
    return models


#: methods whose mutations are construction, not concurrency:
#: nothing else can hold the object yet
CONSTRUCTION_METHODS = frozenset(("__init__", "__new__"))


def entry_contexts(cls):
    """{method: set(frozenset(locks))} — every lock context a method
    can be ENTERED under, propagated over the intra-class call graph
    to a fixpoint.

    Roots: public methods, dunders, and private methods with no
    intra-class caller start at the empty context (external callers
    hold nothing we can prove). A private method that IS called
    intra-class inherits exactly its call sites' contexts — the
    ``_foo_locked``-style convention where the caller holds the lock.
    Closure-borne calls contribute the EMPTY context (the closure may
    run on any thread)."""
    contexts = {}
    called_privately = set()
    for method in cls.methods.values():
        for site in method.calls:
            if site.callee in cls.methods:
                called_privately.add(site.callee)
    for name, method in cls.methods.items():
        externally_reachable = (not method.is_private
                                or name in cls.thread_targets
                                or name not in called_privately)
        contexts[name] = {frozenset()} if externally_reachable else set()
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, method in cls.methods.items():
            for site in method.calls:
                if site.callee not in cls.methods:
                    continue
                site_locks = frozenset() if site.nested is not None \
                    else cls.expand(site.locks)
                for entry in list(contexts[name]):
                    ctx = frozenset(entry | site_locks)
                    if ctx not in contexts[site.callee]:
                        contexts[site.callee].add(ctx)
                        changed = True
        if not changed:
            break
    # a method somehow never rooted (unreachable private): analyze it
    # under the conservative empty context rather than skipping it
    for name in contexts:
        if not contexts[name]:
            contexts[name] = {frozenset()}
    return contexts


def method_roots(cls):
    """{method: set(root tags)} — which entry points can reach each
    method, over the same call graph. Tags: ``thread:<name>`` for
    Thread-target methods, ``public:<name>`` for everything
    externally reachable."""
    roots = {name: set() for name in cls.methods}
    for name, method in cls.methods.items():
        if name in cls.thread_targets:
            roots[name].add("thread:" + name)
        elif not method.is_private or not _has_intra_callers(cls, name):
            roots[name].add("public:" + name)
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, method in cls.methods.items():
            for site in method.calls:
                if site.callee not in cls.methods:
                    continue
                before = len(roots[site.callee])
                roots[site.callee] |= roots[name]
                changed = changed or len(roots[site.callee]) > before
        if not changed:
            break
    return roots


def _has_intra_callers(cls, name):
    for method in cls.methods.values():
        for site in method.calls:
            if site.callee == name:
                return True
    return False
