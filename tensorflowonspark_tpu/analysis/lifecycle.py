"""Pass 3: thread-lifecycle and retriable-taxonomy rules.

Module-wide (not per-class) rules:

- ``thread-daemon`` / ``thread-name`` — every ``threading.Thread(``
  construction must pass ``daemon=`` explicitly (the interpreter's
  default silently decides whether teardown hangs or the thread is
  killed mid-write; the choice must be visible at the spawn site) and
  ``name=`` (chaos/stuck-session triage attributes stacks by thread
  name; an unnamed ``Thread-7`` is unattributable).
- ``thread-unjoined`` — a spawned thread must be reachable from a
  ``join()`` (``self._thread = Thread(...)`` with ``self._thread.
  join(...)`` anywhere in the class; a local with a local join), be
  handed off (returned / passed into a tracking structure), or be
  registered as INTENTIONALLY unjoined with
  ``# tfos: unjoined(<reason>)`` on the spawn line — fire-and-forget
  must be a written decision, not an accident.
- ``retriable-swallow`` — an ``except`` naming the serving retriable
  taxonomy (``Retriable`` / ``Shed`` / ``Draining`` /
  ``EngineFailed`` / ``NoReplicaAvailable`` / ``ReplicaUnavailable``)
  must re-raise or map the error onward (a ``raise``, a ``return``,
  or a call into the pinned HTTP mapping surface — ``_send`` /
  ``_send_json`` / ``http_retriable`` / ...); silently eating a
  retriable turns backpressure into a hang. Suppress with
  ``# tfos: swallow(<reason>)``.
"""

import ast

from tensorflowonspark_tpu.analysis.core import call_name, self_attr
from tensorflowonspark_tpu.analysis.report import Finding

#: the serving retriable taxonomy (serving.py's Retriable tree plus
#: the fleet's two router-side members) — an except naming one of
#: these is load-bearing error routing, not cleanup
RETRIABLE_TAXONOMY = frozenset((
    "Retriable", "Shed", "Draining", "EngineFailed",
    "NoReplicaAvailable", "ReplicaUnavailable"))

#: calls that count as "mapped to a pinned HTTP kind": the serving /
#: fleet handler reply surface and the status->exception translator
HTTP_MAPPERS = frozenset((
    "_send", "_send_json", "send_json", "send_error", "send_response",
    "http_retriable"))


def _qualname(stack):
    return ".".join(stack) or "<module>"


def _thread_label(call, ordinal):
    """Stable baseline identity for one Thread spawn: the literal
    ``name=`` when one exists (a ``"...".format(...)`` call counts —
    the format string is the identity), else the spawn's ordinal
    within its scope."""
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value
        if isinstance(v, ast.Call) \
                and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "format" \
                and isinstance(v.func.value, ast.Constant) \
                and isinstance(v.func.value.value, str):
            return v.func.value.value
        if isinstance(v, ast.BinOp) \
                and isinstance(v.left, ast.Constant) \
                and isinstance(v.left.value, str):
            return v.left.value
    return "#{}".format(ordinal)


def _has_kw(call, name):
    return any(kw.arg == name for kw in call.keywords)


def _joined_in(scope_node, var=None, attr=None):
    """True when ``<var>.join(`` / ``self.<attr>.join(`` appears
    anywhere under ``scope_node`` — including through a one-hop local
    alias (``t = self._thread; ...; t.join()``, the snapshot idiom
    lock-discipline fixes themselves introduce)."""
    aliases = set()
    if attr is not None:
        for node in ast.walk(scope_node):
            if isinstance(node, ast.Assign) \
                    and self_attr(node.value) == attr:
                aliases.update(t.id for t in node.targets
                               if isinstance(t, ast.Name))
    for node in ast.walk(scope_node):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            continue
        owner = node.func.value
        if isinstance(owner, ast.Name) \
                and (owner.id == var or owner.id in aliases):
            return True
        if attr is not None and self_attr(owner) == attr:
            return True
    return False


def _escapes(scope_node, var):
    """True when local ``var`` is returned or passed into a call —
    ownership handed off; tracking the join is the receiver's job."""
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Return) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == var:
            return True
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == var:
                    return True
    return False


class _Walker(object):
    def __init__(self, path, parents):
        self.path = path
        self.parents = parents
        self.findings = []
        self._thread_ordinals = {}
        self._except_ordinals = {}

    # -- thread rules ----------------------------------------------------

    def _enclosing(self, node, kinds):
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, kinds):
            cur = self.parents.get(cur)
        return cur

    def thread_call(self, call, stack):
        qual = _qualname(stack)
        ordinal = self._thread_ordinals.get(qual, 0) + 1
        self._thread_ordinals[qual] = ordinal
        label = _thread_label(call, ordinal)
        ident = "{}:thread:{}".format(qual, label)
        # Timer takes neither daemon= nor name= in its constructor —
        # the explicit choice is an attribute assignment on the bound
        # variable (timer.daemon = True) in the same scope
        var, attr = self._binding(call)
        scope = self._enclosing(
            call, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module))
        if not _has_kw(call, "daemon") and not self._attr_set(
                scope, "daemon", var=var, attr=attr):
            self.findings.append(Finding(
                "thread-daemon", self.path, call.lineno, ident,
                "Thread spawn in {} does not set daemon explicitly "
                "(the default silently decides whether teardown hangs "
                "or kills the thread mid-write)".format(qual)))
        if not _has_kw(call, "name") and not self._attr_set(
                scope, "name", var=var, attr=attr):
            self.findings.append(Finding(
                "thread-name", self.path, call.lineno, ident,
                "Thread spawn in {} is unnamed (name=\"tfos-...\" is "
                "how chaos/stuck-session triage attributes "
                "stacks)".format(qual)))
        self._check_join(call, qual, ident)

    def _binding(self, call):
        """(local_var, self_attr) the spawn is assigned to, either
        possibly None."""
        parent = self.parents.get(call)
        if isinstance(parent, ast.Assign) and parent.value is call \
                and len(parent.targets) == 1:
            target = parent.targets[0]
            attr = self_attr(target)
            if attr is not None:
                return None, attr
            if isinstance(target, ast.Name):
                return target.id, None
        return None, None

    @staticmethod
    def _attr_set(scope, field, var=None, attr=None):
        """True when ``<var>.<field> = ...`` / ``self.<attr>.<field>
        = ...`` appears under ``scope`` — the Timer idiom for daemon
        and name."""
        if scope is None or (var is None and attr is None):
            return False
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not (isinstance(target, ast.Attribute)
                        and target.attr == field):
                    continue
                owner = target.value
                if var is not None and isinstance(owner, ast.Name) \
                        and owner.id == var:
                    return True
                if attr is not None and self_attr(owner) == attr:
                    return True
        return False

    def _check_join(self, call, qual, ident):
        parent = self.parents.get(call)
        func_scope = self._enclosing(
            call, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module))
        if isinstance(parent, ast.Assign) and parent.value is call:
            targets = parent.targets
            if len(targets) == 1:
                attr = self_attr(targets[0])
                if attr is not None:
                    cls_scope = self._enclosing(call, (ast.ClassDef,))
                    scope = cls_scope if cls_scope is not None \
                        else func_scope
                    if scope is not None \
                            and _joined_in(scope, attr=attr):
                        return
                elif isinstance(targets[0], ast.Name):
                    var = targets[0].id
                    if func_scope is not None and (
                            _joined_in(func_scope, var=var)
                            or _escapes(func_scope, var)):
                        return
        self.findings.append(Finding(
            "thread-unjoined", self.path, call.lineno, ident,
            "Thread spawned in {} is reachable from no join() and "
            "not registered as intentionally unjoined "
            "(# tfos: unjoined(<reason>))".format(qual)))

    # -- retriable-swallow -----------------------------------------------

    @staticmethod
    def _caught_taxonomy(type_node):
        names = []
        nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        for n in nodes:
            if isinstance(n, ast.Name):
                names.append(n.id)
            elif isinstance(n, ast.Attribute):
                names.append(n.attr)
        return sorted(set(names) & RETRIABLE_TAXONOMY)

    def except_handler(self, handler, stack):
        if handler.type is None:
            return
        caught = self._caught_taxonomy(handler.type)
        if not caught:
            return
        for node in ast.walk(handler):
            if isinstance(node, (ast.Raise, ast.Return)):
                return
            if isinstance(node, ast.Call) \
                    and call_name(node) in HTTP_MAPPERS:
                return
            # building an error body with a "kind" field IS the pinned
            # HTTP mapping, even when the actual send happens later
            if isinstance(node, ast.Dict) and any(
                    isinstance(k, ast.Constant) and k.value == "kind"
                    for k in node.keys):
                return
        qual = _qualname(stack)
        key = (qual, tuple(caught))
        ordinal = self._except_ordinals.get(key, 0) + 1
        self._except_ordinals[key] = ordinal
        self.findings.append(Finding(
            "retriable-swallow", self.path, handler.lineno,
            "{}:except:{}:#{}".format(qual, "+".join(caught), ordinal),
            "except {} in {} neither re-raises nor maps to an HTTP "
            "kind — swallowing a retriable turns backpressure into a "
            "hang".format("/".join(caught), qual)))

    # -- the walk --------------------------------------------------------

    def walk(self, node, stack):
        for child in ast.iter_child_nodes(node):
            pushed = None
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                pushed = child.name
            if isinstance(child, ast.Call) \
                    and call_name(child) in ("Thread", "Timer"):
                self.thread_call(child, stack)
            if isinstance(child, ast.ExceptHandler):
                self.except_handler(child, stack)
            self.walk(child,
                      stack + [pushed] if pushed else stack)


def check(tree, path):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    walker = _Walker(path, parents)
    walker.walk(tree, [])
    return walker.findings
