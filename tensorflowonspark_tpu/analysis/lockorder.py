"""Pass 2: lock-order audit.

Builds each class's lock-acquisition graph — an edge A->B for every
place B is acquired while A is held, from nested ``with`` statements
directly and from intra-class calls (a method that acquires B, called
under A, is an A->B edge at the call site; acquisition sets propagate
transitively to a fixpoint). Two findings:

- ``lock-order`` — a cycle (A-under-B in one method, B-under-A in
  another): the two-thread deadlock shape PRs 6 and 13 hardened by
  hand (supervisor-quiesce vs rolling-drain, controller vs engine
  ``_cv``). One finding per cycle, keyed on the canonical rotation so
  the baseline identity is stable.
- ``lock-self-nest`` — re-acquiring a non-reentrant ``Lock`` already
  held (directly, via a call chain, or via a
  ``Condition(self._lock)`` alias): not an ordering hazard but a
  guaranteed single-thread deadlock.

Suppress with ``# tfos: lock-order(<reason>)`` on the acquisition
site named in the finding (e.g. a ``Condition.wait`` that releases
the outer lock before the inner acquisition runs — the one shape the
AST cannot see).
"""

from tensorflowonspark_tpu.analysis import core
from tensorflowonspark_tpu.analysis.report import Finding


def _acquired_closure(cls):
    """{method: frozenset(locks)} — locks each method may acquire,
    directly or through intra-class calls, to a fixpoint."""
    acquired = {name: set(m.acquires) for name, m in cls.methods.items()}
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, method in cls.methods.items():
            for site in method.calls:
                if site.callee not in cls.methods:
                    continue
                before = len(acquired[name])
                acquired[name] |= acquired[site.callee]
                changed = changed or len(acquired[name]) > before
        if not changed:
            break
    return acquired


def _edges(cls):
    """{(outer, inner): (method, line)} — first witness per edge."""
    acquired = _acquired_closure(cls)
    edges = {}
    for name, method in cls.methods.items():
        for outer, inner, line in method.with_edges:
            edges.setdefault((outer, inner), (name, line))
        for site in method.calls:
            if site.callee not in cls.methods:
                continue
            held = cls.expand(site.locks)
            for outer in held:
                for inner in acquired[site.callee]:
                    edges.setdefault((outer, inner),
                                     (name, site.line))
    return edges


def _cycles(edges):
    """Canonicalized simple cycles in the edge dict (tiny graphs:
    lock counts per class are single digits, so a DFS over all
    simple paths is exact and cheap)."""
    adj = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, set()).add(b)
    seen = set()
    cycles = []

    def dfs(start, node, path):
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                rot = min(range(len(path)),
                          key=lambda i: path[i])
                canon = tuple(path[rot:] + path[:rot])
                if canon not in seen:
                    seen.add(canon)
                    cycles.append(list(canon))
            elif nxt not in path and nxt > start:
                # only walk nodes > start: each cycle is discovered
                # exactly once, from its smallest member
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return cycles


def check(models):
    findings = []
    for cls in models:
        edges = _edges(cls)
        for (a, b), (method, line) in sorted(edges.items()):
            if a == b and cls.locks.get(a) == "Lock":
                findings.append(Finding(
                    "lock-self-nest", cls.path, line,
                    "{}:{}".format(cls.name, a),
                    "non-reentrant Lock self.{} is re-acquired while "
                    "already held (via {}, line {}); threading.Lock "
                    "deadlocks on re-entry".format(a, method, line)))
        for cycle in _cycles(edges):
            path = cycle + [cycle[0]]
            witness = []
            for i in range(len(cycle)):
                method, line = edges[(path[i], path[i + 1])]
                witness.append("{} under {} at {}:{}".format(
                    path[i + 1], path[i], method, line))
            findings.append(Finding(
                "lock-order", cls.path,
                edges[(path[0], path[1])][1],
                "{}:{}".format(cls.name, "->".join(path)),
                "lock-order cycle in {}: {} — two threads taking "
                "these in opposite order deadlock".format(
                    cls.name, "; ".join(witness))))
    return findings
