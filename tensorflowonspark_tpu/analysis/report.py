"""Shared finding / exit-code report helper for the repo's lint gates.

``make metrics-lint`` and ``make racecheck`` are the same kind of
thing — a pure-python drift gate that either agrees with the tree or
prints an actionable list and exits 1 — so they render through ONE
helper: a gate that formats its failures differently from its sibling
is a gate operators learn to skim past. Pure python, no jax, no
third-party imports: both gates are ``make test`` prerequisites and
must be safe to run before anything heavy is importable.
"""

import sys


class Finding(object):
    """One verified lint finding.

    ``rule`` names the check (``unguarded``, ``lock-order``, ...),
    ``path``/``line`` locate it, ``ident`` is the STABLE identity the
    baseline keys on — file-relative and line-free, so reformatting a
    file does not churn the baseline (``Class.method:attr`` for guard
    findings, ``Class:a->b->a`` for lock cycles, ...). ``message`` is
    the human sentence."""

    def __init__(self, rule, path, line, ident, message, lines=None):
        self.rule = str(rule)
        self.path = str(path)
        self.line = int(line or 0)
        self.ident = str(ident)
        self.message = str(message)
        #: every source line an inline suppression may sit on — a
        #: multi-site finding (e.g. cross-thread, which pairs a
        #: thread-root site with a public one) accepts a suppression
        #: at ANY of its sites; defaults to the anchor line
        self.lines = tuple(lines) if lines else (self.line,)

    @property
    def key(self):
        """Baseline identity: ``rule:path:ident`` (no line numbers)."""
        return "{}:{}:{}".format(self.rule, self.path, self.ident)

    def __repr__(self):
        return "Finding({}:{}: [{}] {})".format(
            self.path, self.line, self.rule, self.message)


def emit(gate, findings, ok_summary="", stale=(), notes=(),
         out=sys.stdout, err=sys.stderr):
    """Render a gate's verdict and return its exit code.

    ``findings``: NEW findings (suppressed/baselined ones are the
    caller's bookkeeping — pass what should fail the build).
    ``ok_summary``: the one green line (e.g. ``"81 families, code and
    docs agree"``). ``stale``: baseline keys that no longer match any
    finding — a warning, not a failure (the fix landed; the entry
    should be pruned). ``notes``: extra context lines printed either
    way. Exit code 0 when ``findings`` is empty, 1 otherwise."""
    for note in notes:
        print("{}: {}".format(gate, note), file=out)
    for key in stale:
        print("{} WARNING: stale baseline entry (no matching finding; "
              "prune it): {}".format(gate, key), file=err)
    if findings:
        print("{} FAILED ({} finding(s)):".format(gate, len(findings)),
              file=err)
        for f in findings:
            where = "{}:{}".format(f.path, f.line) if f.line else f.path
            print("  - {}: [{}] {}".format(where, f.rule, f.message),
                  file=err)
            print("      key: {}".format(f.key), file=err)
        return 1
    print("{}: {}".format(gate, ok_summary or "clean"), file=out)
    return 0
