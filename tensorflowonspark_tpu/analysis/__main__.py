"""``python -m tensorflowonspark_tpu.analysis`` == ``make racecheck``."""

import sys

from tensorflowonspark_tpu.analysis.racecheck import main

sys.exit(main())
