"""Concurrency lint plane: AST-based static analysis as a merge gate.

Four of the last eight PRs' review-hardening passes caught the same
bug class by hand: unlocked read-modify-writes on shared state touched
from HTTP handler threads, BEAT agents, and supervisor polls (router
histogram writes, the goodput ledger's compile-claim, twice a chaos
request counter). ``make metrics-lint`` already proved the pattern
that works — turn a review finding into a CI failure — so this
package does the same for data races. Three passes, stdlib ``ast``
only (no new dependencies, safe as a default-test-target
prerequisite):

1. **Guarded-attribute race check** (:mod:`guards`) — per class,
   infer the guard set (attributes mutated inside ``with self._lock``
   / ``with self._cv`` / any ``threading.Lock|RLock|Condition``
   attribute anywhere in the class) and flag every mutation,
   augmented assignment, or read-modify-write of a guarded attribute
   outside that guard — including mutations in private methods
   reached only from unlocked contexts (intra-class call graph,
   lock state propagated to a fixpoint). A second rule flags
   CROSS-THREAD mutations: an attribute a class's own thread body
   (``Thread(target=self._loop)``) and any other entry point both
   mutate with no lock held anywhere.
2. **Lock-order audit** (:mod:`lockorder`) — build the per-class
   lock-acquisition graph from nested ``with`` statements and
   intra-class call edges; a cycle (A-under-B in one method,
   B-under-A in another) is an error, and so is re-entering a
   non-reentrant ``Lock`` the caller already holds.
3. **Thread-lifecycle rules** (:mod:`lifecycle`) — every
   ``Thread(...)`` must pass ``daemon=`` and ``name=`` explicitly and
   be reachable from a ``join()`` (or be registered as intentionally
   unjoined); every ``except`` that catches the serving retriable
   taxonomy must re-raise or map to a pinned HTTP kind, not swallow.

Findings are suppressed inline with the ``# tfos: <rule>(<reason>)``
grammar (``unguarded`` / ``unjoined`` / ``daemon`` / ``lock-order`` /
``swallow`` — the reason is MANDATORY; an empty one is itself a
finding) or baselined in ``analysis/baseline.json`` (pre-existing
benign findings, each entry carrying a written reason, so the gate
fails loudly on NEW findings only). ``make racecheck`` runs the
driver (:mod:`racecheck`) over the live package; it shares the
finding/exit-code report helper (:mod:`report`) with
``scripts/metrics_lint.py`` so the two gates render identically.

See docs/static_analysis.md for the rule catalog, the suppression
grammar, and the fix-vs-baseline workflow.
"""

from tensorflowonspark_tpu.analysis.report import Finding, emit  # noqa: F401
