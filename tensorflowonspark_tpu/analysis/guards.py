"""Pass 1: guarded-attribute race check.

Per class, two rules over the :mod:`core` model, both evaluated on
EFFECTIVE lock contexts — the locks held at the mutation site itself
plus every context its method can be entered under, propagated over
the intra-class call graph to a fixpoint. That is what makes the
``_apply_locked``-style convention (public method takes the lock,
private helpers mutate bare) analyzable instead of a false positive,
and a helper reachable from BOTH a locked and an unlocked path a
finding instead of a miss:

- ``unguarded`` — infer the guard set: an attribute CONSISTENTLY
  covered by some lock at one or more mutation sites (every effective
  context of that site holds it) is guarded by that lock; every other
  mutation of it that can execute without the guard — plain
  assignment, augmented assignment, an in-place mutator call
  (``self._x.pop(...)``), or any mutation in a method reachable from
  an unlocked context — is flagged. Mutations in
  ``__init__``/``__new__`` are construction, not concurrency, and are
  exempt on both sides of the inference.

- ``cross-thread`` — for classes that spawn their own threads
  (``Thread(target=self._loop)`` or a closure target), an attribute
  mutated lock-free both from a thread root and from any OTHER entry
  point (public method, another thread) is shared mutable state with
  no guard at all: the exact shape of the router-histogram /
  chaos-counter / compile-claim bugs the last four PRs fixed by hand.
  One finding per attribute.

Both rules suppress with ``# tfos: unguarded(<reason>)`` on (or one
line above) the mutation site, and baseline by the line-free identity
``Class.method:attr`` / ``Class:attr``.
"""

from tensorflowonspark_tpu.analysis import core
from tensorflowonspark_tpu.analysis.report import Finding


def _effective_sets(cls, contexts, method, mutation):
    """Every lock set ``mutation`` can execute under: its local locks
    joined with each entry context of its method. A closure's entry
    is unknowable from the definition site (it may run on any thread),
    so only its local locks count."""
    local = cls.expand(mutation.locks)
    if mutation.nested is not None:
        return {local}
    return {frozenset(entry | local)
            for entry in contexts[method.name]}


def _site_table(cls):
    """[(method, mutation, EFF set-of-frozensets)] for every
    non-construction mutation, plus the inferred guard map
    {attr: frozenset(locks)} — a lock guards an attr when SOME
    mutation site is covered by it in every effective context."""
    contexts = core.entry_contexts(cls)
    sites = []
    guards = {}
    for name, method in cls.methods.items():
        if name in core.CONSTRUCTION_METHODS:
            continue
        for m in method.mutations:
            eff = _effective_sets(cls, contexts, method, m)
            sites.append((method, m, eff))
            covered = frozenset.intersection(*eff) if eff else frozenset()
            if covered:
                guards.setdefault(m.attr, set()).update(covered)
    return sites, {attr: frozenset(locks)
                   for attr, locks in guards.items()}


def _mutation_roots(cls, roots, method, mutation):
    """Root tags for one mutation: a closure that is a Thread target
    roots its mutations on that thread, everything else inherits the
    enclosing method's reachability."""
    if mutation.nested is not None \
            and mutation.nested in method.thread_nested:
        return {"thread:{}.{}".format(method.name, mutation.nested)}
    return roots.get(method.name, set())


def check(models):
    """[:class:`Finding`] for a list of class models."""
    findings = []
    for cls in models:
        sites, guards = _site_table(cls)
        findings.extend(_check_unguarded(cls, sites, guards))
        findings.extend(_check_cross_thread(cls, sites, guards))
    return findings


def _check_unguarded(cls, sites, guards):
    if not guards:
        return []
    out = []
    seen = set()
    for method, m, eff in sites:
        guard = guards.get(m.attr)
        if guard is None:
            continue
        if not any(not (s & guard) for s in eff):
            continue  # every reachable context holds a guard lock
        # one finding PER SITE (same baseline key for every site of a
        # method+attr pair, so the baseline still blankets the method
        # while the inline suppression grammar stays exact: a comment
        # silences ITS line, not its siblings)
        site_id = (method.name, m.attr, m.line)
        if site_id in seen:
            continue
        seen.add(site_id)
        out.append(Finding(
            "unguarded", cls.path, m.line,
            "{}.{}:{}".format(cls.name, method.name, m.attr),
            "self.{} is guarded by {} elsewhere in {} but can be "
            "mutated without it at line {} ({})".format(
                m.attr, "/".join(sorted(guard)), cls.name, m.line,
                method.name)))
    out.sort(key=lambda f: f.line)
    return out


def _check_cross_thread(cls, sites, guards):
    if not cls.thread_targets and not any(
            m.thread_nested for m in cls.methods.values()):
        return []
    roots = core.method_roots(cls)
    by_attr = {}
    for method, m, eff in sites:
        if m.attr in guards:
            continue  # the unguarded rule owns inconsistencies
        if frozenset() not in eff:
            continue  # never reachable truly lock-free
        tags = _mutation_roots(cls, roots, method, m)
        rec = by_attr.setdefault(m.attr, {"tags": set(), "sites": []})
        rec["tags"] |= tags
        rec["sites"].append((method.name, m.line))
    findings = []
    for attr in sorted(by_attr):
        rec = by_attr[attr]
        threads = {t for t in rec["tags"] if t.startswith("thread:")}
        others = rec["tags"] - threads
        if not threads or not (others or len(threads) > 1):
            continue
        sites_sorted = sorted(set(rec["sites"]), key=lambda s: s[1])
        findings.append(Finding(
            "cross-thread", cls.path, sites_sorted[0][1],
            "{}:{}".format(cls.name, attr),
            "self.{} is mutated with no lock from {} AND {} "
            "(sites: {})".format(
                attr, ", ".join(sorted(threads)),
                ", ".join(sorted(others)) or "a second thread root",
                "; ".join("{}:{}".format(n, ln)
                          for n, ln in sites_sorted[:6])),
            # the finding is about the PAIR of roots, so a suppression
            # at ANY of its sites (the author asserting the attr's
            # discipline) silences it
            lines=[ln for _, ln in sites_sorted]))
    return findings
