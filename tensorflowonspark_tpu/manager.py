"""Per-executor queue broker: the process-boundary bridge of the feed plane.

Reference: ``tensorflowonspark/TFManager.py`` (SURVEY.md §2 "Queue broker"):
a ``multiprocessing.managers.BaseManager`` serving one joinable queue per
canonical name ('input', 'output', 'error') plus a shared k/v dict (cluster
state machine: 'running' | 'terminating' | 'stopped'), authkey-protected,
bound to localhost ('local' mode) or the executor's routable IP ('remote'
mode, for engines that run worker processes on other hosts).

This broker bridges the *feeder* process (runs data tasks, owns no TPU) and
the *trainer* process (runs the user map_fun, owns the TPU). TPU-native
throughput fix (SURVEY.md §7.3 "Feed throughput"): queue items are batches
(lists of records), assembled feeder-side — the reference's per-record
manager-proxy round trip is its known bottleneck and is deliberately not
reproduced. The manager proxy then costs one round trip per *chunk*, and
``DataFeed`` re-slices chunks to the requested batch size.
"""

import logging
import os
import queue as _queue
import threading
from multiprocessing.managers import BaseManager

logger = logging.getLogger(__name__)

# Canonical queue names (reference: TFSparkNode.run's `queues` default).
QUEUES_TRAIN = ["input", "error"]
QUEUES_INFERENCE = ["input", "output", "error"]


class _KV(object):
    """Lock-protected k/v store (cluster state machine + endpoint info)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            return self._d.get(key)

    def set(self, key, value):
        with self._lock:
            self._d[key] = value


class _Control(object):
    """Server-side helper: queue operations the stock proxy lacks.

    ``join(qname, timeout)`` is the load-bearing one: a feeder must be able
    to wait for its partition to be consumed *without* blocking forever when
    the trainer has died (the reference's bare ``queue.join()`` can hang
    exactly that way; SURVEY.md §5 failure-detection notes feed timeouts as
    the mitigation — this makes the timeout enforceable during the join).
    """

    def __init__(self, qdict):
        self._qdict = qdict

    def join(self, qname, timeout):
        """True once all items put to ``qname`` were task_done'd."""
        import time as _time
        q = self._qdict[qname]
        deadline = _time.monotonic() + timeout
        with q.all_tasks_done:
            while q.unfinished_tasks:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                q.all_tasks_done.wait(left)
        return True


class _ManagerBase(BaseManager):
    pass


class ManagerClient(object):
    """Handle to a broker, same API surface as the reference's TFManager.

    ``get_queue(name)`` returns a shared joinable-queue proxy (put/get/
    task_done/join/qsize/empty all forwarded); ``get``/``set`` hit the shared
    k/v store. Proxies are cached per name — manager round trips are per
    *operation*, not per lookup.
    """

    def __init__(self, mgr, address, authkey, local=None):
        self._mgr = mgr
        self.address = tuple(address)
        self.authkey = authkey
        self._kv = None
        self._control = None
        self._qcache = {}
        self._lock = threading.Lock()
        # In-process fast path: when the broker server runs in THIS process
        # (manager.start), ``local`` carries the real (qdict, kv, control)
        # objects and every operation is a direct call — no proxy pickling,
        # no TCP round trip. The reference pays a manager-proxy hop even
        # for same-process access (TFManager 'local' mode); on a feed plane
        # moving tens of MB per chunk that hop is measurable, so it's gone.
        # The fork-safety note: a forked child inherits a COPY of these
        # objects, so children must never reuse an inherited client —
        # node.py's trainer always reconnects via (address, authkey).
        self._local_pid = os.getpid() if local else None
        self._local = local

    def _use_local(self):
        return self._local is not None and os.getpid() == self._local_pid

    def get_queue(self, qname):
        if self._use_local():
            return self._local[0][qname]
        with self._lock:
            if qname not in self._qcache:
                self._qcache[qname] = self._mgr.get_queue(qname)
            return self._qcache[qname]

    def _kv_proxy(self):
        with self._lock:
            if self._kv is None:
                self._kv = self._mgr.get_kv()
            return self._kv

    def get(self, key):
        if self._use_local():
            return self._local[1].get(key)
        return self._kv_proxy().get(key)

    def set(self, key, value):
        if self._use_local():
            return self._local[1].set(key, value)
        return self._kv_proxy().set(key, value)

    def join_queue(self, qname, timeout):
        """Bounded-wait queue join; True if fully consumed (see _Control)."""
        if self._use_local():
            return self._local[2].join(qname, timeout)
        with self._lock:
            if self._control is None:
                self._control = self._mgr.get_control()
            control = self._control
        return control.join(qname, timeout)


#: Max chunks buffered per DATA (input-like) queue. Bounded so (a) a
#: feeder ahead of the trainer backpressures instead of ballooning
#: broker RAM, and (b) the queue.Full path in the feed closures (state
#: checks, feed_timeout) is live. Sized for COLUMNAR chunks
#: (node.FEED_CHUNK=256 records — a 224px uint8 image chunk is ~38MB):
#: 16 chunks ≈ 600MB ceiling and ~16 device batches of runway.
QUEUE_MAXSIZE = 16

#: Output/error queues hold small result rows, not bulk frames, and the
#: inference pattern feeds the WHOLE partition before draining results
#: (node._inference) — so they get a deep bound: a shallow one would
#: wedge trainer batch_results against the input backpressure until
#: feed_timeout.
RESULT_QUEUE_MAXSIZE = 256


def start(authkey, queues, mode="local", host=None, maxsize=QUEUE_MAXSIZE):
    """Start a broker server in a daemon thread of *this* process.

    Returns a connected :class:`ManagerClient` (``.address`` is the
    endpoint to publish via the reservation meta). Reference:
    ``TFManager.start(authkey, queues, mode)``.

    The reference spawns the manager as a forked server process so it
    survives Spark's python-worker recycling; our engine's executor
    processes are long-lived, so a daemon server thread suffices and dies
    with the node — one less orphan to reap on task retry.
    """
    qdict = {name: _queue.Queue(
        maxsize=RESULT_QUEUE_MAXSIZE if name in ("output", "error")
        else maxsize) for name in queues}
    kv = _KV()
    kv.set("state", "running")

    class _Server(_ManagerBase):
        pass

    # Registered callables return *proxies* to server-held objects — exactly
    # right for the shared queues and the kv store. Value-returning calls
    # (kv.get) happen as proxy *method* calls, which return real values.
    control = _Control(qdict)
    _Server.register("get_queue", callable=lambda qname: qdict[qname])
    _Server.register("get_kv", callable=lambda: kv)
    _Server.register("get_control", callable=lambda: control)

    if mode == "remote":
        if host is None:
            from tensorflowonspark_tpu.util import get_ip_address
            host = get_ip_address()
        address = (host, 0)
    else:
        address = ("127.0.0.1", 0)

    mgr = _Server(address=address, authkey=authkey)
    server = mgr.get_server()
    # tfos: unjoined(process-lifetime queue broker; serve_forever ends with the executor process)
    threading.Thread(target=server.serve_forever, name="tfmanager-server",
                     daemon=True).start()
    # get_server() binds immediately, so server.address is final here.
    client = connect(server.address, authkey,
                     local=(qdict, kv, control))
    logger.info("queue broker listening at %s (mode=%s)", server.address, mode)
    return client


def connect(address, authkey, local=None):
    """Connect to a broker from a sibling process.

    Reference: ``TFManager.connect(addr, authkey)``. Callers in freshly
    spawned processes must first set
    ``multiprocessing.current_process().authkey`` (the node runtime does).
    ``local`` is manager.start's same-process fast path — see
    :class:`ManagerClient`.
    """

    class _Client(_ManagerBase):
        pass

    _Client.register("get_queue")
    _Client.register("get_kv")
    _Client.register("get_control")
    mgr = _Client(address=tuple(address), authkey=authkey)
    mgr.connect()
    return ManagerClient(mgr, address, authkey, local=local)
