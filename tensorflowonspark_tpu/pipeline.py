"""ML-pipeline layer: Estimator/Model with Spark-ML-shaped params.

Reference: ``tensorflowonspark/pipeline.py`` (SURVEY.md §2 "Spark ML
Pipeline", §3.4): ~15 ``HasXxx`` param mixins, a ``Namespace``/``TFParams``
merger, ``TFEstimator(train_fn, tf_args)._fit(df)`` spinning up a cluster,
and ``TFModel._transform(df)`` doing single-node parallel inference with a
per-process cached loaded model (no cluster).

The TPU-native export format is :mod:`tensorflowonspark_tpu.export`
(apply_fn + orbax variables), replacing TF SavedModel signatures; the
input/output column mapping semantics are unchanged.
"""

import copy
import logging

from tensorflowonspark_tpu import cluster
from tensorflowonspark_tpu.engine.dataframe import DataFrame

logger = logging.getLogger(__name__)


class Namespace(object):
    """Attribute bag, argparse-Namespace compatible (reference:
    ``pipeline.Namespace``): construct from a dict or another namespace."""

    def __init__(self, d=None, **kwargs):
        if d is not None:
            self.__dict__.update(d if isinstance(d, dict) else vars(d))
        self.__dict__.update(kwargs)

    def __contains__(self, key):
        return key in self.__dict__

    def __iter__(self):
        return iter(self.__dict__)

    def __eq__(self, other):
        return isinstance(other, Namespace) and vars(self) == vars(other)

    def __repr__(self):  # pragma: no cover - debug aid
        return "Namespace({})".format(self.__dict__)


def _param(name, default=None, doc=""):
    """Generate a Spark-ML-style param property + setter/getter pair."""

    private = "_" + name

    def getter(self):
        return getattr(self, private, default)

    def setter(self, value):
        setattr(self, private, value)
        return self

    return getter, setter


class _ParamsBase(object):
    """Spark-ML param plumbing: setXxx/getXxx for every declared param.

    Reference: the ``HasXxx`` mixin family + ``TFParams``. Params are
    declared in ``PARAMS`` as (name, default); accessors are generated
    (``setBatchSize``/``getBatchSize`` for ``batch_size``), and ``merge``
    folds the set values into the user's args namespace the way
    ``TFParams.merge_args_params`` does.
    """

    PARAMS = ()

    def __init__(self, tf_args=None):
        self.args = Namespace(tf_args) if tf_args is not None else Namespace()
        self._set_params = {}

    def _set(self, name, value):
        self._set_params[name] = value
        return self

    def _get(self, name):
        if name in self._set_params:
            return self._set_params[name]
        for pname, default in type(self).PARAMS:
            if pname == name:
                return getattr(self.args, name, default)
        raise KeyError(name)

    def __getattr__(self, attr):
        # setBatchSize / getBatchSize style accessors
        if attr.startswith(("set", "get")) and len(attr) > 3:
            snake = _camel_to_snake(attr[3:])
            if any(p == snake for p, _ in type(self).PARAMS):
                if attr.startswith("set"):
                    return lambda value: self._set(snake, value)
                return lambda: self._get(snake)
        raise AttributeError(attr)

    def merged_args(self):
        """args namespace + every explicitly set param (param wins)."""
        merged = Namespace(self.args)
        for pname, default in type(self).PARAMS:
            if getattr(merged, pname, None) is None:
                setattr(merged, pname, default)
        merged.__dict__.update(self._set_params)
        return merged


def _camel_to_snake(name):
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


#: the reference's HasXxx surface (SURVEY.md §2 pipeline row)
_COMMON_PARAMS = (
    ("batch_size", 100),
    ("epochs", 1),
    ("cluster_size", 1),
    ("num_ps", 0),
    ("input_mode", "spark"),
    ("input_mapping", None),      # {df column -> feed name}
    ("output_mapping", None),     # {model output -> df column}
    ("model_dir", None),
    ("export_dir", None),
    ("signature_def_key", "serving_default"),
    ("tag_set", "serve"),
    ("protocol", "grpc"),
    ("tensorboard", False),
    ("master_node", "chief"),
    ("tfrecord_dir", None),
    ("grace_secs", 0),
)


class TFEstimator(_ParamsBase):
    """Train on a DataFrame via a cluster; produces a :class:`TFModel`.

    Reference: ``pipeline.TFEstimator(train_fn, tf_args, export_fn)``.
    ``train_fn(args, ctx)`` is a normal map_fun; it should export to
    ``args.export_dir`` on the chief (via ``export.save_model``).
    """

    PARAMS = _COMMON_PARAMS

    def __init__(self, train_fn, tf_args=None, export_fn=None):
        super(TFEstimator, self).__init__(tf_args)
        self.train_fn = train_fn
        self.export_fn = export_fn

    def fit(self, df):
        return self._fit(df)

    def _fit(self, df):
        args = self.merged_args()
        sc = df.rdd.ctx
        logger.info("TFEstimator.fit: cluster_size=%d input_mode=%s",
                    args.cluster_size, args.input_mode)
        input_mode = (cluster.InputMode.SPARK if args.input_mode == "spark"
                      else cluster.InputMode.TENSORFLOW)
        tfc = cluster.run(sc, self.train_fn, args,
                          num_executors=args.cluster_size,
                          num_ps=args.num_ps,
                          tensorboard=args.tensorboard,
                          input_mode=input_mode,
                          log_dir=args.model_dir,
                          master_node=args.master_node)
        if input_mode == cluster.InputMode.SPARK:
            # feed rows as input_mapping-ordered tuples (reference behavior:
            # df columns selected per input_mapping, in mapping order)
            mapping = args.input_mapping or {c: c for c in df.columns}
            cols = list(mapping.keys())
            rdd = df.rdd.map(lambda row, _c=tuple(cols):
                             [row[k] for k in _c])
            tfc.train(rdd, num_epochs=args.epochs)
        tfc.shutdown(grace_secs=args.grace_secs)
        return TFModel(copy.deepcopy(vars(args)))


class TFModel(_ParamsBase):
    """Single-node parallel inference over DataFrame partitions.

    Reference: ``pipeline.TFModel._transform`` — no cluster; every
    executor loads (and caches) the exported model, maps ``input_mapping``
    columns to model inputs, batches rows, emits ``output_mapping``
    columns (SURVEY.md §3.4).
    """

    PARAMS = _COMMON_PARAMS

    def __init__(self, tf_args=None):
        super(TFModel, self).__init__(tf_args)

    def transform(self, df):
        return self._transform(df)

    def _transform(self, df):
        args = self.merged_args()
        if not args.export_dir:
            raise ValueError("TFModel requires export_dir")
        in_mapping = args.input_mapping or {}
        out_mapping = args.output_mapping or {}
        export_dir = args.export_dir
        batch_size = args.batch_size

        def _run_model(iterator):
            # cached per executor process (export.load_model caches)
            import numpy as np

            from tensorflowonspark_tpu import export as export_lib

            apply_fn, variables, signature = export_lib.load_model(export_dir)
            inputs = in_mapping or {c: c for c in signature.get("inputs", [])}
            outputs = out_mapping or {
                c: c for c in signature.get("outputs", [])}

            import itertools

            it = iter(iterator)
            while True:
                chunk = list(itertools.islice(it, batch_size))
                if not chunk:
                    break
                batch = {feed: np.asarray([row[col] for row in chunk])
                         for col, feed in inputs.items()}
                result = apply_fn(variables, batch)
                if not isinstance(result, dict):
                    result = {"output": result}
                n = len(chunk)
                for i in range(n):
                    out_row = {}
                    for model_out, col in outputs.items():
                        value = np.asarray(result[model_out])[i]
                        out_row[col] = value.tolist() \
                            if value.ndim > 0 else value.item()
                    yield out_row

        result_rdd = df.rdd.mapPartitions(_run_model)

        # Honest output schema, lazily: dtypes come from the first real
        # result row (the way dfutil infers from the first Example) but
        # only if/when the schema is actually read — take(1) then costs a
        # single one-row task, and the loaded model stays cached on that
        # executor for the full pass. Empty input falls back to the
        # declared columns as float32.
        def _infer_output_schema():
            first = result_rdd.take(1)
            if first:
                from tensorflowonspark_tpu.engine.dataframe import (
                    _infer_dtype)
                return [(c, _infer_dtype(v)) for c, v in first[0].items()]
            out_cols = list((out_mapping or {"output": "output"}).values())
            return [(c, "float32") for c in out_cols]

        return DataFrame(result_rdd, _infer_output_schema)
