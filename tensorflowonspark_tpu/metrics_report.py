"""Shared metric-reading and report-formatting helpers.

One home for the percentile and stage-printing code that was
copy-pasted across ``bench.py``, ``scripts/profile_serving.py``,
``scripts/profile_recovery.py``, and ``scripts/profile_fed.py``
(each kept a private sample list and its own ``np.percentile`` /
median / stage-table variant). Everything here READS the
observability plane (``tracing.MetricsRegistry`` / ``Histogram`` /
``StageTimers``) — the same objects ``GET /metrics`` exposes — so a
published bench number and a scraped series can never drift: they are
two views of one histogram.

Import discipline: pure python, no jax/numpy — safe in driver
processes that must not initialize a device backend.
"""


def median(values):
    """Middle element of ``values`` (upper median for even counts) —
    the bench's standard multi-rep reducer."""
    values = sorted(values)
    return values[len(values) // 2]


def quantiles_ms(hist, pcts=(50, 95, 99)):
    """{"p50_ms": ..., "p95_ms": ..., "p99_ms": ...} read from a
    ``tracing.Histogram`` (milliseconds, rounded; Nones when the
    histogram is empty)."""
    out = {}
    for p in pcts:
        q = hist.quantile(p / 100.0) if hist is not None else None
        out["p{:g}_ms".format(p)] = None if q is None \
            else round(q * 1e3, 3)
    return out


#: the serving histograms every latency report reads, in report order:
#: {report key: registry family}
SERVING_HISTOGRAMS = (
    ("latency", "tfos_serving_request_seconds"),
    ("ttft", "tfos_serving_ttft_seconds"),
    ("per_token", "tfos_serving_token_latency_seconds"),
    ("decode_step", "tfos_serving_decode_step_seconds"),
    ("queue_wait", "tfos_serving_queue_wait_seconds"),
)


def serving_quantiles(registry, pcts=(50, 95, 99)):
    """Per-histogram latency quantiles from a serving engine's
    registry: {latency, ttft, per_token, decode_step, queue_wait} ->
    quantile dicts. The block ``bench.py serving_decode`` publishes and
    ``scripts/profile_serving.py`` prints — read from the SAME
    histograms ``GET /metrics`` renders."""
    return {key: quantiles_ms(registry.get_histogram(family), pcts)
            for key, family in SERVING_HISTOGRAMS}


def stage_ms(timers):
    """{stage: mean ms per sample} from a ``tracing.StageTimers`` —
    the human-readable per-stage attribution every profile prints."""
    return timers.per_ms()


def stage_totals_s(timers):
    """{stage: total seconds, rounded} from a ``StageTimers``."""
    return {k: round(v, 3) for k, v in timers.snapshot().items()}


def format_stage_ms(timers):
    """One-line ``stage=ms`` rendering of :func:`stage_ms`, sorted by
    cost — the compact form the fed profiles log per run."""
    per = stage_ms(timers)
    return "  ".join("{}={}".format(k, per[k])
                     for k in sorted(per, key=per.get, reverse=True))


def format_goodput(report):
    """Multi-line rendering of a goodput report (``goodput.
    GoodputLedger.report`` or ``goodput.job_report`` shape): headline
    ratio, then the badput table sorted by cost with each category's
    share of wall time — what ``scripts/goodput_report.py`` prints and
    the bench's goodput leg logs."""
    wall = report.get("wall_s") or 0.0
    lines = ["goodput {:6.2%}  (productive {:.3f}s of {:.3f}s wall)"
             .format(report.get("goodput_ratio", 0.0),
                     report.get("productive_s", 0.0), wall)]
    badput = report.get("badput") or {}
    for category in sorted(badput, key=badput.get, reverse=True):
        seconds = badput[category]
        if not seconds:
            continue
        lines.append("  badput {:16s} {:9.3f}s  ({:5.1%})".format(
            category, seconds, seconds / wall if wall else 0.0))
    unacc = report.get("unaccounted_s")
    if unacc is not None:
        lines.append("  unaccounted {:+.3f}s ({:+.2%} of wall)".format(
            unacc, unacc / wall if wall else 0.0))
    return "\n".join(lines)


def format_straggler_table(rows):
    """Straggler table from per-executor skew rows
    ``[{executor, skew, step_ewma_s?}]`` (or a plain {executor: skew}
    dict), worst first."""
    if isinstance(rows, dict):
        rows = [{"executor": eid, "skew": skew}
                for eid, skew in rows.items()]
    if not rows:
        return "no step-time skew data (no executor has stepped yet)"
    lines = ["{:>10s} {:>8s} {:>14s}".format(
        "executor", "skew", "step_ewma_ms")]
    for row in sorted(rows, key=lambda r: -(r.get("skew") or 0)):
        ewma = row.get("step_ewma_s")
        lines.append("{:>10s} {:>8.2f} {:>14s}".format(
            str(row.get("executor")), float(row.get("skew") or 0.0),
            "-" if ewma is None else "{:.3f}".format(ewma * 1e3)))
    return "\n".join(lines)
