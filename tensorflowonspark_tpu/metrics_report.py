"""Shared metric-reading and report-formatting helpers.

One home for the percentile and stage-printing code that was
copy-pasted across ``bench.py``, ``scripts/profile_serving.py``,
``scripts/profile_recovery.py``, and ``scripts/profile_fed.py``
(each kept a private sample list and its own ``np.percentile`` /
median / stage-table variant). Everything here READS the
observability plane (``tracing.MetricsRegistry`` / ``Histogram`` /
``StageTimers``) — the same objects ``GET /metrics`` exposes — so a
published bench number and a scraped series can never drift: they are
two views of one histogram.

Import discipline: pure python, no jax/numpy — safe in driver
processes that must not initialize a device backend.
"""


def median(values):
    """Middle element of ``values`` (upper median for even counts) —
    the bench's standard multi-rep reducer."""
    values = sorted(values)
    return values[len(values) // 2]


def quantiles_ms(hist, pcts=(50, 95, 99)):
    """{"p50_ms": ..., "p95_ms": ..., "p99_ms": ...} read from a
    ``tracing.Histogram`` (milliseconds, rounded; Nones when the
    histogram is empty)."""
    out = {}
    for p in pcts:
        q = hist.quantile(p / 100.0) if hist is not None else None
        out["p{:g}_ms".format(p)] = None if q is None \
            else round(q * 1e3, 3)
    return out


#: the serving histograms every latency report reads, in report order:
#: {report key: registry family}
SERVING_HISTOGRAMS = (
    ("latency", "tfos_serving_request_seconds"),
    ("ttft", "tfos_serving_ttft_seconds"),
    ("per_token", "tfos_serving_token_latency_seconds"),
    ("decode_step", "tfos_serving_decode_step_seconds"),
    ("queue_wait", "tfos_serving_queue_wait_seconds"),
)


def serving_quantiles(registry, pcts=(50, 95, 99)):
    """Per-histogram latency quantiles from a serving engine's
    registry: {latency, ttft, per_token, decode_step, queue_wait} ->
    quantile dicts. The block ``bench.py serving_decode`` publishes and
    ``scripts/profile_serving.py`` prints — read from the SAME
    histograms ``GET /metrics`` renders."""
    return {key: quantiles_ms(registry.get_histogram(family), pcts)
            for key, family in SERVING_HISTOGRAMS}


def stage_ms(timers):
    """{stage: mean ms per sample} from a ``tracing.StageTimers`` —
    the human-readable per-stage attribution every profile prints."""
    return timers.per_ms()


def stage_totals_s(timers):
    """{stage: total seconds, rounded} from a ``StageTimers``."""
    return {k: round(v, 3) for k, v in timers.snapshot().items()}


def format_stage_ms(timers):
    """One-line ``stage=ms`` rendering of :func:`stage_ms`, sorted by
    cost — the compact form the fed profiles log per run."""
    per = stage_ms(timers)
    return "  ".join("{}={}".format(k, per[k])
                     for k in sorted(per, key=per.get, reverse=True))


def format_goodput(report):
    """Multi-line rendering of a goodput report (``goodput.
    GoodputLedger.report`` or ``goodput.job_report`` shape): headline
    ratio, then the badput table sorted by cost with each category's
    share of wall time — what ``scripts/goodput_report.py`` prints and
    the bench's goodput leg logs."""
    wall = report.get("wall_s") or 0.0
    lines = ["goodput {:6.2%}  (productive {:.3f}s of {:.3f}s wall)"
             .format(report.get("goodput_ratio", 0.0),
                     report.get("productive_s", 0.0), wall)]
    badput = report.get("badput") or {}
    for category in sorted(badput, key=badput.get, reverse=True):
        seconds = badput[category]
        if not seconds:
            continue
        lines.append("  badput {:16s} {:9.3f}s  ({:5.1%})".format(
            category, seconds, seconds / wall if wall else 0.0))
    unacc = report.get("unaccounted_s")
    if unacc is not None:
        lines.append("  unaccounted {:+.3f}s ({:+.2%} of wall)".format(
            unacc, unacc / wall if wall else 0.0))
    return "\n".join(lines)


def format_slo_verdict(verdict):
    """Multi-line rendering of a ``GET /slo`` verdict document: one
    headline per spec (budget remaining + firing state), then the
    window/burn table — what ``scripts/slo_report.py`` prints and the
    bench's slo leg logs."""
    lines = []
    for spec in verdict.get("specs") or []:
        budget = spec.get("error_budget_remaining")
        lines.append(
            "slo {:16s} tenant={:12s} {}  budget {}".format(
                spec.get("slo", "?"), spec.get("tenant", "?"),
                "FIRING" if spec.get("firing") else "ok    ",
                "n/a" if budget is None
                else "{:7.2%}".format(budget)))
        for window in spec.get("windows") or []:
            lines.append(
                "    window {:>6g}s/{:>6g}s  burn {:>8s}/{:>8s}  "
                "(threshold {:g}x{})".format(
                    window.get("short_s", 0), window.get("long_s", 0),
                    _burn(window.get("short_burn")),
                    _burn(window.get("long_burn")),
                    window.get("threshold", 0),
                    ", firing" if window.get("firing") else ""))
    alerts = verdict.get("alerts_total") or {}
    if any(alerts.values()):
        lines.append("alerts raised: " + "  ".join(
            "{}={}".format(name, alerts[name])
            for name in sorted(alerts) if alerts[name]))
    return "\n".join(lines) if lines else "no SLO specs configured"


def _burn(value):
    return "-" if value is None else "{:.2f}x".format(value)


def format_canary(canary):
    """Canary summary block from a verdict's ``canary`` section (or
    ``None`` when no prober is attached)."""
    if not canary:
        return "canary: not attached"
    counters = canary.get("counters") or {}
    lines = ["canary: {} probes, {} failures, {} drift{}".format(
        counters.get("probes", 0), counters.get("failures", 0),
        counters.get("drift", 0),
        "" if canary.get("expected_pinned")
        else "  (expected tokens not pinned yet)")]
    history = canary.get("history") or []
    for record in history[-8:]:
        lines.append(
            "  probe ok={} status={} latency={:.1f}ms{}{}".format(
                record.get("ok"), record.get("status"),
                (record.get("latency_s") or 0.0) * 1e3,
                " DRIFT" if record.get("drift") else "",
                "" if not record.get("error")
                else " ({})".format(record["error"])))
    return "\n".join(lines)


def format_attribution(report):
    """Per-request critical-path table from an ``slo.attribute_trace``
    report: stage seconds sorted by cost with shares of wall — what
    ``scripts/explain_request.py`` prints for one trace id."""
    wall = report.get("wall_s") or 0.0
    lines = ["request wall {:.3f}s".format(wall)]
    stages = report.get("stages") or {}
    for stage in sorted(stages, key=stages.get, reverse=True):
        seconds = stages[stage]
        if not seconds:
            continue
        lines.append("  {:16s} {:9.3f}s  ({:5.1%})".format(
            stage, seconds, seconds / wall if wall else 0.0))
    unattributed = report.get("unattributed_s")
    if unattributed:
        lines.append("  {:16s} {:9.3f}s  ({:5.1%})".format(
            "unattributed", unattributed,
            unattributed / wall if wall else 0.0))
    return "\n".join(lines)


def format_straggler_table(rows):
    """Straggler table from per-executor skew rows
    ``[{executor, skew, step_ewma_s?}]`` (or a plain {executor: skew}
    dict), worst first."""
    if isinstance(rows, dict):
        rows = [{"executor": eid, "skew": skew}
                for eid, skew in rows.items()]
    if not rows:
        return "no step-time skew data (no executor has stepped yet)"
    lines = ["{:>10s} {:>8s} {:>14s}".format(
        "executor", "skew", "step_ewma_ms")]
    for row in sorted(rows, key=lambda r: -(r.get("skew") or 0)):
        ewma = row.get("step_ewma_s")
        lines.append("{:>10s} {:>8.2f} {:>14s}".format(
            str(row.get("executor")), float(row.get("skew") or 0.0),
            "-" if ewma is None else "{:.3f}".format(ewma * 1e3)))
    return "\n".join(lines)
