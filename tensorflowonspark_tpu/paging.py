"""Block-granular KV cache accounting: free-list allocator + prefix cache.

The HOST half of the paged KV cache (PR 8). The device half lives in
models/decoder.py (a ``[num_blocks, block_size, heads, dim]`` K/V pool
per attention layer, gathered through per-slot block tables); this
module owns which block holds what:

- **free-list allocation** — blocks are fixed-size; a sequence consumes
  ``ceil(len / block_size)`` of them as it grows instead of reserving a
  contiguous ``max_len`` region up front (the PagedAttention insight:
  KV fragmentation drops to at most one partial block per sequence, so
  memory — not compute — stops capping concurrency).
- **ref-counted prefix sharing** — full blocks of a sequence are
  registered under their token chain (the key for block ``j`` is the
  EXACT token tuple ``sequence[:(j+1)*block_size]``, so a hit
  guarantees the whole prefix matches — content-addressed, no hash
  collisions to reason about). A later request whose prompt starts
  with the same tokens points its block table at the shared blocks and
  prefills only the tail. Shared blocks are read-only by construction:
  only COMPLETE blocks are ever shared, and a sharer's write cursor
  starts at the first position past them — so "copy-on-write on the
  first divergent block" degenerates to allocating a fresh private
  block (there is nothing to copy; divergent content simply prefills
  into it). Registrations carry an ``origin`` ("prompt" at admission,
  "generated" when the engine publishes a block DECODE filled — PR
  11), so multi-turn reuse — a follow-up turn whose prompt IS the
  prior turn's prompt + reply — is separately countable from repeated
  system prompts.
- **LRU retention** — a released block that is registered in the prefix
  cache is RETAINED (refcount 0, evictable) rather than freed, so the
  next same-prefix request still hits; under allocation pressure the
  least-recently-released cached blocks are evicted back into
  circulation. ``allocatable()`` counts both (free + evictable): it is
  the number the admission gate and the ``kv_blocks_free`` gauge read.

Block id 0 is the SCRATCH block: never allocated, parked in every
unused block-table entry. Prefill pads prompts to a shape bucket, and
the pad positions' K/V writes land through the table — scratch absorbs
them. Its content is garbage by design and is never visible (attention
masks every position past a row's cursor). The device pool therefore
carries ``num_blocks + 1`` rows for a pool of ``num_blocks`` usable
blocks.

Single-writer convention: the engine's scheduler thread is the only
mutator. The internal lock exists so observers (``load_stats``,
``/healthz``, admission estimates on client threads) can read
consistent counts, not to support concurrent mutation.
"""

import collections
import hashlib
import threading

import numpy as np


#: bytes per stored K/V element by pool dtype (bfloat16 has no numpy
#: dtype, so an explicit table beats np.dtype here)
_KV_ITEMSIZE = {"int8": 1, "float16": 2, "bfloat16": 2, "float32": 4,
                "float64": 8}

#: default chain budget of :meth:`BlockPool.prefix_digest` — the
#: BOUNDED part of the fleet's prefix-warmth signal. A beat payload
#: must stay small at any pool size, so a pool with thousands of
#: registered chains still publishes at most this many (the hottest),
#: with ``truncated`` flagging what was cut.
PREFIX_DIGEST_TOP_K = 32

#: hex chars of the truncated chain hash a digest entry carries: 16
#: hex = 64 bits, so accidental collisions across a fleet's worth of
#: resident chains are negligible while the entry stays compact
_DIGEST_HASH_HEX = 16


def chain_digest(tokens, n_tokens):
    """Truncated stable hash of the EXACT chain key ``tokens[:n_tokens]``
    — the wire form of a prefix chain in the beat-carried digest. Both
    sides of the fleet's warmth matching use this one function (the
    pool when publishing, the router when probing a prompt's chain
    prefixes against a replica's digest), so the two can never drift.
    Canonical serialization is the comma-joined decimal token ids:
    content-addressed like the registry itself, independent of process,
    platform, and hash seed (sha1, not ``hash()``)."""
    key = ",".join(str(int(t)) for t in list(tokens)[:int(n_tokens)])
    return hashlib.sha1(key.encode("ascii")).hexdigest()[:_DIGEST_HASH_HEX]


class PoolExhausted(RuntimeError):
    """``alloc`` could not supply the requested blocks even after
    evicting every unreferenced cached block. The engine's scheduler
    preempts or defers admission instead of letting this escape."""


class BlockPool(object):
    """Free-list allocator over ``num_blocks`` usable KV blocks of
    ``block_size`` tokens each (ids ``1..num_blocks``; id 0 is the
    scratch block pad writes land in — see module docstring).

    ``hits``/``misses`` count prefix-cache outcomes at BLOCK
    granularity (a request with 12 shareable full blocks that finds 8
    resident scores 8 hits + 4 misses); ``evictions`` counts cached
    blocks reclaimed by the LRU under allocation pressure.
    """

    def __init__(self, num_blocks, block_size, kv_dtype="float32"):
        if int(num_blocks) < 1:
            raise ValueError(
                "num_blocks must be >= 1, got {}".format(num_blocks))
        if int(block_size) < 1:
            raise ValueError(
                "block_size must be >= 1, got {}".format(block_size))
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        #: storage dtype of the device pools this allocator governs
        #: (PR 15): "int8" means each block additionally carries
        #: per-head float32 scales per token row — :meth:`block_bytes`
        #: is the byte accounting, :meth:`quantize` the host reference
        #: of the write-path formulation. The allocator's BLOCK math
        #: (blocks_for / plan / alloc) is dtype-independent: a block
        #: holds block_size tokens either way, it just costs fewer
        #: bytes quantized.
        self.kv_dtype = str(kv_dtype)
        if self.kv_dtype not in _KV_ITEMSIZE:
            raise ValueError(
                "kv_dtype must be one of {}, got {!r}".format(
                    sorted(_KV_ITEMSIZE), kv_dtype))
        self._lock = threading.Lock()
        # LIFO free list: recently freed blocks are re-handed first
        self._free = list(range(self.num_blocks, 0, -1))
        self._ref = {}                # id -> refcount (> 0: live)
        self._by_key = {}             # token-chain key -> block id
        self._key_of = {}             # block id -> its registered key
        self._origin = {}             # block id -> "prompt"/"generated"
        # refcount-0 blocks still registered in the prefix cache, in
        # least-recently-released-first order (the eviction order)
        self._lru = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # generated-prefix accounting (PR 11): registrations of
        # decode-filled blocks, and the subset of hits that landed on
        # one — the multi-turn reuse signal load_stats surfaces
        self.generated_registered = 0
        self.generated_hits = 0
        # per-block hit tally (PR 16): how often each REGISTERED block
        # was found resident by a chain walk — the heat signal
        # :meth:`prefix_digest` ranks its top-K hottest chains by.
        # Dropped with the registration (eviction / drop_cache), so a
        # recycled block id never inherits a prior chain's heat.
        self._chain_hits = {}
        # mutation epoch: bumped by every state change that could alter
        # an admission verdict (alloc/release/acquire/register/
        # drop_cache). The engine's blocked-head memo keys on it — a
        # raw allocatable() reading can return to a memoized value
        # while a registration changed the head's need underneath it.
        self._epoch = 0

    # -- sizing ----------------------------------------------------------

    def blocks_for(self, n_tokens):
        """Blocks a sequence of ``n_tokens`` occupies (ceil)."""
        if n_tokens <= 0:
            return 0
        return (int(n_tokens) + self.block_size - 1) // self.block_size

    def block_bytes(self, num_heads, head_dim, layers=1):
        """Resident device bytes ONE block costs across ``layers``
        attention layers: K + V codes at :attr:`kv_dtype`, plus the
        per-head float32 scales int8 blocks carry alongside. The
        number ``estimate_admission``'s byte pricing and the
        ``serving_decode.kv_int8`` bench's fixed-byte-budget math
        read — int8 at head_dim 16 costs 40 bytes/token/layer/KV-pair
        vs float32's 128, so the same budget buys ~3.2x the blocks."""
        per_token = 2 * num_heads * head_dim * _KV_ITEMSIZE[self.kv_dtype]
        if self.kv_dtype == "int8":
            per_token += 2 * num_heads * 4  # the float32 scales
        return self.block_size * per_token * int(layers)

    @staticmethod
    def quantize(x):
        """Numpy mirror of ``ops.paged_attention.quantize_kv`` — the
        host reference the device write path is pinned against:
        ``[..., D]`` float -> (int8 codes, float32 per-head scales),
        symmetric absmax over the last axis, zero vectors to zero
        codes under scale 1.0. Same exact-round-trip fixed point:
        requantizing the dequantized grid reproduces codes and scales
        bitwise (tests/test_speculative.py pins numpy == jnp)."""
        x = np.asarray(x)
        # cast BEFORE dividing, exactly like the device op: dividing
        # in a wider input dtype (float64 numpy default) then casting
        # double-rounds the scale, shifting codes by ±1 vs the device
        s = np.max(np.abs(x), axis=-1).astype(np.float32) / 127.0
        s = np.where(s > 0, s, np.float32(1.0))
        q = np.clip(np.round(x.astype(np.float32) / s[..., None]),
                    -127, 127).astype(np.int8)
        return q, s

    @staticmethod
    def dequantize(q, s):
        """Inverse of :meth:`quantize`: codes x scales, float32."""
        return np.asarray(q, np.float32) \
            * np.asarray(s, np.float32)[..., None]

    def allocatable(self):
        """Blocks an ``alloc`` could supply right now: the free list
        plus every evictable (refcount-0) cached block."""
        with self._lock:
            return len(self._free) + len(self._lru)

    def stats(self):
        """{'total', 'free', 'cached', 'live', 'hits', 'misses',
        'evictions', 'hit_rate'} — the numbers ``load_stats`` /
        ``/healthz`` / the BEAT payload surface. ``free`` is
        ALLOCATABLE (free list + evictable cache); ``cached`` the
        evictable subset; ``live`` blocks referenced by in-flight
        sequences."""
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "total": self.num_blocks,
                "kv_dtype": self.kv_dtype,
                "free": len(self._free) + len(self._lru),
                "cached": len(self._lru),
                "live": len(self._ref),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / lookups) if lookups else 0.0,
                "generated_registered": self.generated_registered,
                "generated_hits": self.generated_hits,
            }

    def prefix_digest(self, top_k=PREFIX_DIGEST_TOP_K):
        """Compact, bounded digest of the RESIDENT prefix-chain
        registry — the per-replica warmth signal the serving beat
        carries and the fleet router's prefix-aware dispatch matches
        prompts against (PR 16).

        ``{'block_size', 'top': [[hash, depth], ...], 'truncated'}``:
        each entry is one registered chain as its truncated
        :func:`chain_digest` plus its depth in FULL blocks, hottest
        first (per-block hit tally desc, then depth desc — a deep
        resident conversation outranks a shallow one at equal heat —
        then the chain key itself, so the ordering is deterministic
        for a given registry state). Generated-origin chains are
        included exactly like prompt-origin ones: a turn-2 prompt
        matches the chain decode just extended. At most ``top_k``
        entries are published no matter how many chains are resident;
        ``truncated`` says whether anything was cut — the honesty flag
        that lets a router distinguish "cold" from "warm beyond what
        the digest shows"."""
        top_k = max(1, int(top_k))
        with self._lock:
            chains = [(self._chain_hits.get(bid, 0),
                       len(key) // self.block_size, key)
                      for bid, key in self._key_of.items()]
        chains.sort(key=lambda c: (-c[0], -c[1], c[2]))
        top = [[chain_digest(key, len(key)), depth]
               for _, depth, key in chains[:top_k]]
        return {"block_size": self.block_size, "top": top,
                "truncated": len(chains) > top_k}

    def epoch(self):
        """Mutation counter: changes whenever alloc / release /
        acquire / register / drop_cache changed pool state. Equal
        epochs guarantee an admission plan's verdict is unchanged."""
        with self._lock:
            return self._epoch

    def ref_count(self, block_id):
        """Live refcount of ``block_id`` (0 when unreferenced)."""
        with self._lock:
            return self._ref.get(int(block_id), 0)

    def live_refs(self):
        """{block_id: refcount} for every referenced block — the
        leak-audit view the churn test asserts empties."""
        with self._lock:
            return dict(self._ref)

    # -- prefix cache ----------------------------------------------------

    @staticmethod
    def _chain_key(tokens, n):
        return tuple(tokens[:n])

    def _walk_locked(self, tokens):
        """Longest resident chain of FULL blocks for ``tokens`` (caller
        holds ``_lock``), capped so at least one token is always left
        for the tail prefill (a fully-cached prompt still needs a
        forward pass to produce the logits its first generated token
        samples from). Returns ``(ids, shareable)`` — the ONE chain
        walk behind :meth:`match_prefix` and :meth:`plan`, so the
        admission gate's dry run can never disagree with what admission
        actually acquires."""
        shareable = max(0, (len(tokens) - 1) // self.block_size)
        ids = []
        for j in range(shareable):
            key = self._chain_key(tokens, (j + 1) * self.block_size)
            bid = self._by_key.get(key)
            if bid is None:
                break
            ids.append(bid)
        return ids, shareable

    def match_prefix(self, tokens, count_generated=True):
        """Resident shared-prefix block ids for ``tokens``, in chain
        order. Does NOT take references — call :meth:`acquire` before
        using them. Tallies hits/misses; generated-origin hits tally
        separately unless ``count_generated=False`` — the engine
        passes False for a preemption continuation's re-admission,
        whose walk lands back on the blocks the SAME request
        registered before being preempted (counting those would read
        as multi-turn reuse during a pure pool-pressure storm)."""
        tokens = list(tokens)
        with self._lock:
            ids, shareable = self._walk_locked(tokens)
            self.hits += len(ids)
            self.misses += shareable - len(ids)
            for bid in ids:
                self._chain_hits[bid] = self._chain_hits.get(bid, 0) + 1
            if count_generated:
                self.generated_hits += sum(
                    1 for bid in ids
                    if self._origin.get(bid) == "generated")
        return ids

    def resident_chain(self, tokens, acquire=False):
        """Longest resident chain of FULL blocks for ``tokens``,
        UNCAPPED — the KV-export walk (PR 17 disaggregation). Where
        :meth:`_walk_locked` stops at ``(len - 1) // block_size`` so
        admission always leaves a tail token to prefill, a prefill
        worker exporting a finished prompt wants every block admission
        registered — ``len(tokens) // block_size`` of them — because
        the DEEPEST block is exactly the one a decode-tier adopter
        saves the most prefill on. Tallies no hits (an export probe is
        not a cache lookup). With ``acquire`` the walk takes one
        reference per returned block UNDER THE SAME LOCK — the export
        path needs walk-then-pin to be atomic, or a concurrent
        ``drop_cache`` / eviction could free a block between the two
        (callers :meth:`release` when done). Read-only otherwise.
        Returns ``[(block_id, origin), ...]`` in chain order."""
        tokens = list(tokens)
        out = []
        with self._lock:
            for j in range(len(tokens) // self.block_size):
                key = self._chain_key(tokens, (j + 1) * self.block_size)
                bid = self._by_key.get(key)
                if bid is None:
                    break
                out.append((bid, self._origin.get(bid, "prompt")))
            if acquire and out:
                self._epoch += 1
                for bid, _ in out:
                    self._ref[bid] = self._ref.get(bid, 0) + 1
                    self._lru.pop(bid, None)
        return out

    def plan(self, tokens):
        """(shared_ids, new_blocks_needed, lru_resident) for admitting
        ``tokens`` — the admission gate's dry run (no refs taken, no
        tallies). ``lru_resident`` counts the shared blocks currently
        parked in the LRU: acquiring THOSE removes capacity from
        :meth:`allocatable`, while sharing a LIVE block (another
        in-flight sequence holds a reference) costs nothing — the
        distinction that lets concurrent same-prefix requests admit
        together instead of serializing on a pool-sized prefix.

        NOTE: pricing a plan against capacity needs
        :meth:`plan_admission` — a separate ``allocatable()`` call is
        a SECOND lock acquisition, and the pool can mutate between the
        two (the racecheck triage's torn-read finding: an admission
        estimate on an HTTP handler thread straddling the scheduler's
        ``acquire`` double-counted the deficit and shed feasible
        deadlines)."""
        ids, need, lru_resident, _, _ = self.plan_admission(tokens)
        return ids, need, lru_resident

    def plan_admission(self, tokens):
        """(shared_ids, new_blocks_needed, lru_resident, allocatable,
        epoch) — :meth:`plan` plus the pool's current capacity and
        mutation epoch, all read under ONE lock hold, so the deficit
        ``new_needed + lru_resident - allocatable`` is priced against
        a single consistent snapshot and the epoch provably matches
        the verdict (the blocked-head memo's key). Invariant a torn
        read breaks and this cannot: ``lru_resident`` and
        ``allocatable`` move together when a chain is acquired, so
        ``lru_resident + (total - allocatable)`` never exceeds the
        chain's own length plus the truly-live block count (pinned by
        the concurrent churn test in tests/test_paged_kv.py)."""
        tokens = list(tokens)
        with self._lock:
            ids, _ = self._walk_locked(tokens)
            lru_resident = sum(1 for bid in ids if bid in self._lru)
            allocatable = len(self._free) + len(self._lru)
            epoch = self._epoch
        return (ids, self.blocks_for(len(tokens)) - len(ids),
                lru_resident, allocatable, epoch)

    def register(self, tokens, n_tokens, block_id, origin="prompt"):
        """Publish ``block_id`` as holding the K/V of the FULL block
        ending at ``n_tokens`` (``tokens[:n_tokens]`` is its chain
        key; ``n_tokens`` must be a block multiple). First writer
        wins: if the chain is already registered to another block the
        existing entry stands and this one stays private. ``origin``
        ("prompt" / "generated") tags where the block's content came
        from — the engine registers decode-filled blocks as
        "generated" so multi-turn reuse is separately countable."""
        if n_tokens % self.block_size:
            raise ValueError(
                "register at {} tokens: not a multiple of block_size {}"
                .format(n_tokens, self.block_size))
        key = self._chain_key(tokens, n_tokens)
        with self._lock:
            bid = int(block_id)
            if key in self._by_key or bid in self._key_of:
                return
            if self._ref.get(bid, 0) < 1:
                raise ValueError(
                    "register of unreferenced block {}".format(bid))
            self._by_key[key] = bid
            self._key_of[bid] = key
            self._origin[bid] = str(origin)
            if origin == "generated":
                self.generated_registered += 1
            self._epoch += 1

    def drop_cache(self):
        """Unregister every EVICTABLE cached block and return it to the
        free list (live shared blocks keep their registration). The
        operator's 'flush the prefix cache' hook, and how the leak test
        proves retention is cache, not leak. Returns the count."""
        with self._lock:
            dropped = list(self._lru)
            if dropped:
                self._epoch += 1
            for bid in dropped:
                self._lru.pop(bid)
                key = self._key_of.pop(bid)
                self._by_key.pop(key)
                self._origin.pop(bid, None)
                self._chain_hits.pop(bid, None)
                self._free.append(bid)
            return len(dropped)

    # -- allocation ------------------------------------------------------

    def acquire(self, block_ids):
        """Take one reference on each shared block in ``block_ids`` (a
        refcount-0 cached block leaves the LRU: it is live again)."""
        with self._lock:
            if block_ids:
                self._epoch += 1
            for bid in block_ids:
                bid = int(bid)
                self._ref[bid] = self._ref.get(bid, 0) + 1
                self._lru.pop(bid, None)

    def alloc(self, n):
        """``n`` fresh private blocks (refcount 1 each), from the free
        list first, then by evicting least-recently-released cached
        blocks. Raises :class:`PoolExhausted` (allocating NOTHING) if
        fewer than ``n`` are obtainable."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if len(self._free) + len(self._lru) < n:
                raise PoolExhausted(
                    "need {} block(s); {} free + {} cached evictable "
                    "of {} total".format(n, len(self._free),
                                         len(self._lru), self.num_blocks))
            self._epoch += 1
            ids = []
            while len(ids) < n:
                if self._free:
                    ids.append(self._free.pop())
                    continue
                bid, _ = self._lru.popitem(last=False)  # oldest first
                key = self._key_of.pop(bid)
                self._by_key.pop(key)
                self._origin.pop(bid, None)
                self._chain_hits.pop(bid, None)
                self.evictions += 1
                ids.append(bid)
            for bid in ids:
                self._ref[bid] = 1
            return ids

    def release(self, block_ids):
        """Drop one reference per block. A block reaching refcount 0
        returns to the free list — unless it is registered in the
        prefix cache, in which case it parks in the LRU (evictable,
        still hittable)."""
        with self._lock:
            if block_ids:
                self._epoch += 1
            for bid in block_ids:
                bid = int(bid)
                left = self._ref.get(bid, 0) - 1
                if left < 0:
                    raise ValueError(
                        "release of unreferenced block {}".format(bid))
                if left:
                    self._ref[bid] = left
                    continue
                del self._ref[bid]
                if bid in self._key_of:
                    self._lru[bid] = None
                    self._lru.move_to_end(bid)
                else:
                    self._free.append(bid)
