"""TFRecord + tf.train.Example codec — no TensorFlow dependency.

Reference capability: the ``tensorflow-hadoop`` connector JAR (Java) that
``dfutil`` drove through Spark's Hadoop I/O (SURVEY.md §2 "TFRecord
interop", §2.2 native table). The format is tiny, so the TPU-native build
owns it outright (SURVEY.md §7.2 step 6):

record framing (tfrecord_writer.cc upstream):
    uint64 length | uint32 masked_crc32c(length) | bytes data |
    uint32 masked_crc32c(data)

payload: a ``tf.train.Example`` protobuf —
    Example{ features: Features{ feature: map<string, Feature> } }
    Feature is oneof bytes_list(1) / float_list(2) / int64_list(3).

The proto wire codec below is hand-rolled for exactly this fixed schema
(varint + length-delimited walking), checked in tests against the real
``tensorflow`` serializers as oracle. crc32c comes from the C-accelerated
``google_crc32c`` when present, else a pure-python table fallback.
"""

import io
import os
import struct

import numpy as np

try:
    import google_crc32c

    def _crc32c(data):
        return google_crc32c.value(bytes(data))
except ImportError:  # pragma: no cover - present in the image
    _TABLE = []

    def _crc32c(data, _poly=0x82F63B78):
        if not _TABLE:
            for n in range(256):
                c = n
                for _ in range(8):
                    c = (c >> 1) ^ (_poly if c & 1 else 0)
                _TABLE.append(c)
        crc = 0xFFFFFFFF
        for b in bytes(data):
            crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF


def masked_crc32c(data):
    """TFRecord's rotated+offset crc32c mask."""
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -- record framing --------------------------------------------------------

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")

_NATIVE = None  # tri-state: None = unprobed


def _native_ok():
    """Native codec availability, probed once (TFOS_TFRECORD_NATIVE=0
    opts out)."""
    global _NATIVE
    if _NATIVE is None:
        if os.environ.get("TFOS_TFRECORD_NATIVE", "1") != "1":
            _NATIVE = False
        else:
            try:
                from tensorflowonspark_tpu import _tfrecord_native
                _NATIVE = _tfrecord_native.available()
            except Exception:  # noqa: BLE001 - pure python remains
                _NATIVE = False
    return _NATIVE


class TFRecordWriter(object):
    """Append-only TFRecord file writer (context manager)."""

    def __init__(self, path):
        from tensorflowonspark_tpu import fs
        self._f = fs.open(path, "wb")  # remote schemes via fs registry

    def write(self, record):
        record = bytes(record)
        header = _U64.pack(len(record))
        self._f.write(header)
        self._f.write(_U32.pack(masked_crc32c(header)))
        self._f.write(record)
        self._f.write(_U32.pack(masked_crc32c(record)))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _read_exact(f, n):
    """Read exactly n bytes (or whatever remains at EOF).

    Registered remote openers (fs.py) may hand back raw/network streams
    whose read() legally returns short — a single read() would then
    misreport intact files as truncated/corrupt.
    """
    chunks = []
    got = 0
    while got < n:
        chunk = f.read(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _try_mmap(f):
    """mmap of an open local REGULAR file for the native scan, or None.

    None means "not mmap-able" — sockets/pipes/remote streams (a socket's
    fileno fstats as size 0, which must not read as an empty file) and
    openers without a usable fileno. ``f`` is NOT closed either way, so a
    one-shot stream opener keeps its handle for the streaming fallback."""
    import mmap
    import stat as stat_mod

    try:
        st = os.fstat(f.fileno())
        if not stat_mod.S_ISREG(st.st_mode):
            return None
        if st.st_size == 0:
            return b""
        return mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (AttributeError, OSError, ValueError, io.UnsupportedOperation):
        return None


def tfrecord_iterator(path, verify_crc=True):
    """Yield raw record bytes from a TFRecord file (fs registry handles
    remote schemes).

    Fast path: when the native codec builds (``_tfrecord_native``) AND
    the path is a local regular file, the file is mmapped and framing +
    both CRCs are validated in one C scan before the first yield. Each
    record is materialised as ``bytes`` either way, so the yielded type
    never depends on whether the host could build the codec (zero-copy
    views stay internal to :func:`read_batch`, where the native dense
    decode consumes them without the copy). Note the eagerness
    tradeoff: the whole file is validated up front, so consuming only
    the first records of a huge file is cheaper via
    :func:`first_record` or the python loop below — which remains the
    canonical fallback and the only remote-stream path (it never
    buffers the file in RAM)."""
    from tensorflowonspark_tpu import fs
    f = fs.open(path, "rb")
    buf = _try_mmap(f) if _native_ok() else None
    if buf is not None:
        from tensorflowonspark_tpu import _tfrecord_native
        f.close()
        for view in _tfrecord_native.iter_records(buf, verify_crc):
            yield bytes(view)
        return
    with f:
        for data in _iter_stream(f, verify_crc):
            yield data


def _iter_stream(f, verify_crc):
    """The lazy per-record loop over an OPEN stream (never buffers the
    file; the only path for non-mmap-able remote streams)."""
    while True:
        header = _read_exact(f, 8)
        if not header:
            return
        if len(header) < 8:
            raise ValueError("truncated TFRecord length header")
        (length,) = _U64.unpack(header)
        crc_bytes = _read_exact(f, 4)
        if len(crc_bytes) < 4:
            raise ValueError("truncated TFRecord length crc")
        (length_crc,) = _U32.unpack(crc_bytes)
        if verify_crc and masked_crc32c(header) != length_crc:
            raise ValueError("corrupt TFRecord: bad length crc")
        data = _read_exact(f, length)
        if len(data) < length:
            raise ValueError("truncated TFRecord payload")
        crc_bytes = _read_exact(f, 4)
        if len(crc_bytes) < 4:
            raise ValueError("truncated TFRecord data crc")
        (data_crc,) = _U32.unpack(crc_bytes)
        if verify_crc and masked_crc32c(data) != data_crc:
            raise ValueError("corrupt TFRecord: bad data crc")
        yield data


# -- protobuf wire primitives ---------------------------------------------

def _write_varint(buf, value):
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            buf.append(bits | 0x80)
        else:
            buf.append(bits)
            return


def _read_varint(data, pos):
    shift = 0
    result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _tag(field, wire_type):
    return (field << 3) | wire_type


def _write_len_delimited(buf, field, payload):
    _write_varint(buf, _tag(field, 2))
    _write_varint(buf, len(payload))
    buf.extend(payload)


# -- Example encoding ------------------------------------------------------

def _encode_feature(values):
    """values: list of bytes/str | float | int -> Feature message bytes."""
    inner = bytearray()
    if not values:
        return bytes(inner)  # empty Feature (no kind set)
    v0 = values[0]
    if isinstance(v0, (bytes, bytearray, str, np.bytes_)):
        sub = bytearray()
        for v in values:
            if isinstance(v, str):
                v = v.encode("utf-8")
            _write_len_delimited(sub, 1, bytes(v))
        _write_len_delimited(inner, 1, sub)  # bytes_list = field 1
    elif isinstance(v0, (float, np.floating)):
        packed = np.asarray(values, "<f4").tobytes()
        sub = bytearray()
        _write_len_delimited(sub, 1, packed)  # packed floats, field 1
        _write_len_delimited(inner, 2, sub)  # float_list = field 2
    elif isinstance(v0, (int, np.integer, bool)):
        sub = bytearray()
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        _write_len_delimited(sub, 1, packed)  # packed varints, field 1
        _write_len_delimited(inner, 3, sub)  # int64_list = field 3
    else:
        raise TypeError("unsupported feature value type: {}".format(type(v0)))
    return bytes(inner)


def encode_example(features):
    """{name: scalar | list | 1-D ndarray} -> serialized tf.train.Example.

    Type mapping mirrors the reference's ``dfutil.toTFExample``:
    bytes/str -> bytes_list, float -> float_list, int/bool -> int64_list.
    """
    fmap = bytearray()
    # deterministic output: sorted feature names (map order is unspecified
    # in proto, but byte-stable files diff nicely)
    for name in sorted(features):
        values = features[name]
        if isinstance(values, np.ndarray):
            values = values.reshape(-1).tolist()
        elif not isinstance(values, (list, tuple)):
            values = [values]
        entry = bytearray()
        _write_len_delimited(entry, 1, name.encode("utf-8"))  # key
        _write_len_delimited(entry, 2, _encode_feature(list(values)))  # value
        _write_len_delimited(fmap, 1, bytes(entry))  # map entry: feature=1
    example = bytearray()
    _write_len_delimited(example, 1, bytes(fmap))  # features = field 1
    return bytes(example)


# -- Example decoding ------------------------------------------------------

def _iter_fields(data):
    """Yield (field_number, wire_type, value, next_pos) over a message."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = _read_varint(data, pos)
        elif wire == 2:
            length, pos = _read_varint(data, pos)
            value = data[pos:pos + length]
            pos += length
        elif wire == 5:
            value = data[pos:pos + 4]
            pos += 4
        elif wire == 1:
            value = data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError("unsupported wire type {}".format(wire))
        yield field, wire, value


def _decode_packed_varints(data):
    out = []
    pos = 0
    while pos < len(data):
        v, pos = _read_varint(data, pos)
        if v >= 1 << 63:  # two's-complement int64
            v -= 1 << 64
        out.append(v)
    return out


def _decode_feature(data):
    """Feature message -> (kind, values)."""
    for field, wire, value in _iter_fields(data):
        if field == 1:  # bytes_list
            vals = [bytes(v) for f, w, v in _iter_fields(value) if f == 1]
            return "bytes", vals
        if field == 2:  # float_list
            vals = []
            for f, w, v in _iter_fields(value):
                if f != 1:
                    continue
                if w == 2:  # packed
                    vals.extend(np.frombuffer(v, "<f4").tolist())
                else:  # unpacked 32-bit
                    vals.append(struct.unpack("<f", v)[0])
            return "float", vals
        if field == 3:  # int64_list
            vals = []
            for f, w, v in _iter_fields(value):
                if f != 1:
                    continue
                if w == 2:
                    vals.extend(_decode_packed_varints(v))
                else:
                    x = v if isinstance(v, int) else _read_varint(v, 0)[0]
                    if x >= 1 << 63:
                        x -= 1 << 64
                    vals.append(x)
            return "int64", vals
    return "empty", []


def parse_example(data):
    """Serialized Example -> {name: (kind, values)}."""
    out = {}
    for field, wire, value in _iter_fields(data):
        if field != 1:  # features
            continue
        for f, w, entry in _iter_fields(value):
            if f != 1:  # feature map entry
                continue
            name = None
            feat = ("empty", [])
            for ef, ew, ev in _iter_fields(entry):
                if ef == 1:
                    # bytes() no-ops on bytes records and materialises
                    # the memoryview slices _iter_fields produces
                    name = bytes(ev).decode("utf-8")
                elif ef == 2:
                    feat = _decode_feature(ev)
            if name is not None:
                out[name] = feat
    return out


# -- directory-level helpers ----------------------------------------------

def write_tfrecords(path, examples, compress=False):
    """Write an iterable of feature-dicts to one TFRecord file."""
    assert not compress, "compression not supported"
    count = 0
    with TFRecordWriter(path) as w:
        for features in examples:
            w.write(encode_example(features))
            count += 1
    return count


def first_record(path, verify_crc=True):
    """First record's bytes (or None if the file is empty) via the LAZY
    streaming loop — O(one record) of I/O regardless of file size, where
    the native :func:`tfrecord_iterator` path would CRC-scan the whole
    file before yielding. The schema-inference read (dfutil) wants this."""
    from tensorflowonspark_tpu import fs
    with fs.open(path, "rb") as f:
        return next(_iter_stream(f, verify_crc), None)


def count_records(path, verify_crc=True):
    """Number of records in a TFRecord file — metadata-rate via the
    native framing index when available (no per-record python work)."""
    if _native_ok():
        from tensorflowonspark_tpu import _tfrecord_native
        from tensorflowonspark_tpu import fs
        with fs.open(path, "rb") as f:
            buf = _try_mmap(f)
        if buf is not None:
            return len(_tfrecord_native.index_buffer(buf, verify_crc)[0])
    return sum(1 for _ in tfrecord_iterator(path, verify_crc))


def read_examples(path):
    """Yield parsed {name: (kind, values)} dicts from a TFRecord file."""
    for record in tfrecord_iterator(path):
        yield parse_example(record)


def read_batch(path, schema, verify_crc=True):
    """Dense columnar read: ``{name: ndarray[m, width]}`` for a fixed
    schema, in file order.

    ``schema``: ``{feature_name: (dtype, width)}`` with dtype
    ``"float32"``/``"int64"`` — the dense-features shape of the W&D /
    Criteo pipelines, where per-record python parsing dominates load
    time. Uses the native batch decoder when available; falls back to
    :func:`parse_example`. Raises ``ValueError`` when a record misses a
    feature or its arity differs (a dense schema is a contract, not a
    hint).
    """
    for name, (dtype, width) in schema.items():
        if dtype not in ("float32", "int64"):
            raise ValueError(
                "schema dtype for %r must be float32 or int64" % name)
    if _native_ok():
        from tensorflowonspark_tpu import _tfrecord_native
        from tensorflowonspark_tpu import fs
        with fs.open(path, "rb") as f:
            buf = _try_mmap(f)
        if buf is not None:
            offsets, lengths = _tfrecord_native.index_buffer(buf, verify_crc)
            out = {}
            for name, (dtype, width) in schema.items():
                fn = (_tfrecord_native.batch_floats if dtype == "float32"
                      else _tfrecord_native.batch_int64)
                out[name] = fn(buf, offsets, lengths, name, width)
            return out
    columns = {name: [] for name in schema}
    for i, parsed in enumerate(read_examples(path)):
        for name, (dtype, width) in schema.items():
            if name not in parsed:
                raise ValueError(
                    "record %d: feature %r missing, wrong kind, or not "
                    "%d values" % (i, name, width))
            kind, values = parsed[name]
            expect = "float" if dtype == "float32" else "int64"
            if kind != expect or len(values) != width:
                raise ValueError(
                    "record %d: feature %r missing, wrong kind, or not "
                    "%d values" % (i, name, width))
            columns[name].append(values)
    return {name: np.asarray(columns[name],
                             "float32" if schema[name][0] == "float32"
                             else "int64").reshape(len(columns[name]),
                                                   schema[name][1])
            for name in schema}


def list_tfrecord_files(directory):
    """part-* files under ``directory``, sorted (the Hadoop layout).

    Directory listing needs a real filesystem — remote schemes fail
    loudly here (fs.require_local) instead of as an os.listdir ENOENT.
    """
    from tensorflowonspark_tpu import fs

    directory = fs.require_local(directory, "TFRecord shard listing")
    names = [n for n in sorted(os.listdir(directory))
             if n.startswith("part-") and not n.endswith(".crc")]
    return [os.path.join(directory, n) for n in names]
