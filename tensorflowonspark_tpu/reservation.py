"""Cluster-formation barrier + service discovery (the control plane).

Reference: ``tensorflowonspark/reservation.py`` (SURVEY.md §2 "Reservation
service"): a zero-dependency TCP rendezvous hosted on the driver. Every
executor registers its node metadata (host, ports, authkey, role); everyone
blocks until exactly N registrations exist; then every node can fetch the
full cluster_info list. Message types REG / QUERY / QINFO / STOP.

TPU-native differences from the reference's design:

- Wire format is length-prefixed JSON, not pickle: registration messages
  cross trust boundaries (any process that can reach the port), and the
  driver must never unpickle executor-supplied bytes. Binary fields
  (authkeys) travel hex-encoded.
- The barrier's output doubles as the *JAX coordination bootstrap*: once all
  N nodes are registered, node metas are sorted deterministically and the
  chief's (host, coordinator_port) becomes the
  ``jax.distributed.initialize`` coordinator address — the piece
  ``TF_CONFIG`` provided in the reference.
- The service stays up after the barrier opens and carries the
  *supervision plane* (supervisor.py): BEAT messages register per-executor
  heartbeat leases (liveness + a small status payload the driver-side
  Supervisor classifies), and ACK messages record fed partitions as
  consumed so a restart-from-checkpoint recovery replays only the
  unacknowledged ones. The same BEAT leases carry the *fleet plane*
  (fleet.py): serving replicas beat with ``role: "serving"`` payloads
  (HTTP address + live load gauges + engine metrics snapshot), which
  :meth:`Server.serving_snapshot` exposes to the FleetRouter's
  least-loaded dispatch and the ``/stats`` replica view. The reference's server spoke only
  REG/QUERY/QINFO/STOP and went idle after formation (SURVEY.md §5: no
  failure detection beyond Spark task retry).
"""

import json
import logging
import socket
import struct
import threading
import time

from tensorflowonspark_tpu import chaos, tracing

logger = logging.getLogger(__name__)

#: Default seconds to wait for all nodes to register (reference default 600).
DEFAULT_TIMEOUT = 600

#: Default seconds a STARTED message may take to finish arriving before
#: the server gives up on the connection (see MessageSocket). Idle time
#: BETWEEN messages is never bounded — only a half-open / wedged peer
#: that stalled mid-message trips this.
DEFAULT_RECV_DEADLINE = 30.0

_LEN = struct.Struct(">I")
_MAX_MSG = 16 * 1024 * 1024


class TimeoutError_(RuntimeError):
    """Barrier did not complete within the timeout."""


class Fenced(RuntimeError):
    """This beater's lease epoch is STALE: another holder registered
    for the same identity after it (typically: a replacement spawned
    while this one was partitioned away). NON-retriable by design —
    re-beating harder cannot make a superseded lease current; the only
    way back is an explicit re-registration (``Client.lease``), which
    is an operator/supervisor decision, not a retry loop's."""

    def __init__(self, msg, epoch=None):
        super(Fenced, self).__init__(msg)
        #: the CURRENT epoch held by the replacement (None if unknown)
        self.epoch = epoch


class Reservations(object):
    """Thread-safe registry counting up to ``required`` node registrations.

    Reference: ``reservation.Reservations`` — lock-protected list + count.
    """

    def __init__(self, required):
        self.required = required
        self._lock = threading.Condition()
        self._meta = []

    def add(self, meta):
        """Register one node; a re-registration (retried worker) with the
        same executor_id *replaces* the stale entry — it must not double
        count, or the barrier opens early and the sorted-index == process-
        index contract breaks."""
        with self._lock:
            eid = meta.get("executor_id")
            for i, m in enumerate(self._meta):
                if eid is not None and m.get("executor_id") == eid:
                    self._meta[i] = meta
                    break
            else:
                self._meta.append(meta)
            if self.done():
                self._lock.notify_all()

    def done(self):
        return len(self._meta) >= self.required

    def get(self):
        with self._lock:
            return list(self._meta)

    def remaining(self):
        with self._lock:
            return self.required - len(self._meta)

    def wait(self, timeout=DEFAULT_TIMEOUT):
        deadline = time.monotonic() + timeout
        with self._lock:
            while not self.done():
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError_(
                        "timed out waiting for {} of {} node reservations".format(
                            self.required - len(self._meta), self.required))
                self._lock.wait(left)
            return list(self._meta)


class MessageSocket(object):
    """Length-prefixed JSON messages over a stream socket.

    Reference: ``reservation.MessageSocket`` (which framed *pickled* payloads
    — deliberately not reproduced; see module docstring).

    ``recv_deadline`` bounds how long a message that has STARTED
    arriving may take to finish: once any byte of a frame is in, the
    rest (header remainder + body) must land within the deadline or the
    read fails with ``ConnectionError``. Waiting for the FIRST byte of
    the next message stays unbounded — an idle-but-healthy peer (a
    registered client between beats) is normal, but a half-open TCP
    peer that died mid-frame used to wedge the server's handler thread
    in ``recv`` forever. The server arms this on every accepted
    connection (:data:`DEFAULT_RECV_DEADLINE`); clients default to
    unbounded for compatibility.

    ``net_src``/``net_dst`` label this socket's exchanges for the
    chaos network fault plane (``chaos.on_net``); unlabeled sockets
    only match fully-wildcarded injections.
    """

    def __init__(self, sock, recv_deadline=None):
        self.sock = sock
        self.recv_deadline = recv_deadline
        self.net_src = None
        self.net_dst = None

    def send(self, msg):
        data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        frame = _LEN.pack(len(data)) + data
        if chaos.net_armed():
            # instrumented transport site: may raise NetPartitioned
            # (a ConnectionError — callers treat it like a real one)
            # or sleep (net_delay). A one-way send can't lose a
            # response alone, so every loss here is request-side
            # (response_capable=False); and a "dup" action is IGNORED
            # — this is a framed request/response stream over TCP,
            # where the transport cannot duplicate a frame, and
            # re-sending one here would desynchronize the protocol
            # (the peer answers twice, every later call reads the
            # previous call's reply). net_dup models duplicated
            # EXCHANGES, which only the HTTP transport can express.
            chaos.on_net(self.net_src, self.net_dst)
        self.sock.sendall(frame)

    def receive(self):
        header = self._recv_exact(_LEN.size)
        (length,) = _LEN.unpack(header)
        if length > _MAX_MSG:
            raise ValueError("reservation message too large: {} bytes".format(length))
        return json.loads(
            self._recv_exact(length, mid_message=True).decode("utf-8"))

    def _recv_exact(self, n, mid_message=False):
        """Read exactly ``n`` bytes. ``mid_message``: part of the frame
        already arrived, so the whole read is deadline-bounded from
        entry; otherwise the deadline arms only once the first chunk
        lands (waiting for a message to BEGIN is idle, not a stall)."""
        buf = bytearray()
        deadline = None
        if mid_message and self.recv_deadline is not None:
            deadline = time.monotonic() + self.recv_deadline
        while len(buf) < n:
            if deadline is None and buf and self.recv_deadline is not None:
                deadline = time.monotonic() + self.recv_deadline
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise ConnectionError(
                        "reservation peer stalled mid-message "
                        "({}/{} bytes after {}s)".format(
                            len(buf), n, self.recv_deadline))
                self.sock.settimeout(left)
                try:
                    chunk = self.sock.recv(n - len(buf))
                except socket.timeout:
                    raise ConnectionError(
                        "reservation peer stalled mid-message "
                        "({}/{} bytes after {}s)".format(
                            len(buf), n, self.recv_deadline))
                finally:
                    self.sock.settimeout(None)
            else:
                chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("reservation peer closed connection")
            buf.extend(chunk)
        return bytes(buf)

    def close(self):
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class Server(object):
    """Driver-hosted rendezvous server.

    Reference: ``reservation.Server`` — ``start()`` binds an ephemeral port,
    a background thread serves REG/QUERY/QINFO/STOP until stopped.
    """

    def __init__(self, count, recv_deadline=DEFAULT_RECV_DEADLINE,
                 journal=None, recovery_grace=5.0):
        self.reservations = Reservations(count)
        #: mid-message receive deadline armed on every accepted
        #: connection (see MessageSocket) — a half-open peer fails its
        #: handler thread in bounded time instead of wedging it forever
        self.recv_deadline = recv_deadline
        self._sock = None
        self._thread = None
        self._stats_httpd = None
        #: (host, port) of the driver-side stats HTTP endpoint
        #: (/metrics + /stats), set by start(); None if it failed
        self.stats_addr = None
        self.done = threading.Event()
        # supervision plane: heartbeat leases + consumed-partition acks
        # (read by supervisor.Supervisor, which runs in this process)
        self._sup_lock = threading.Lock()
        self._leases = {}   # executor_id -> (monotonic recv time, payload)
        # lease fencing (PR 12): identity -> current epoch, minted
        # monotonically by LEASE messages. Once an identity has an
        # epoch, only beats carrying the CURRENT epoch refresh its
        # lease; anything else is answered FENCED and dropped — a
        # replica re-beating after a partition healed cannot overwrite
        # its replacement's lease (the split-brain double-serve window)
        self._epochs = {}
        self._acked = set()  # partition ids fully consumed by a trainer
        # elastic-resize bookkeeping (ONE source of truth for width:
        # SupervisedCluster sets these at every formation, so /metrics
        # and /stats show the live attempt's width vs the configured
        # target — a shrunken job is visibly degraded, not implicit in
        # Decision.exclude set arithmetic)
        self._cluster_width = None
        self._cluster_width_target = None
        # durable safety floors (PR 19): when a journal is attached,
        # every minted epoch hits disk BEFORE it leaves the building,
        # and a restarted server seeds its mint state from the
        # journal's floors — monotonicity survives restart by
        # construction. `journal` accepts a ControlJournal or a path.
        if isinstance(journal, str):
            from tensorflowonspark_tpu import controlstate
            journal = controlstate.ControlJournal(journal)
        self.journal = journal
        self._control_epoch = 0
        #: identities whose floors came from the journal but whose
        #: incumbents have not re-announced yet (recovery tracking)
        self._awaiting_reannounce = set()
        self._recovery_grace = float(recovery_grace)
        self._recovery_deadline = None  # armed by start() when recovering
        #: cumulative BEAT messages handled (guarded by _sup_lock) —
        #: drives the kill_reservation_server chaos site
        self._beats_seen = 0
        if journal is not None:
            floors = journal.epoch_floors()
            if floors:
                self._epochs.update(floors)
                self._awaiting_reannounce = set(floors)
            self._control_epoch = journal.control_floor()

    def lease_snapshot(self):
        """{executor_id: {"age": seconds since last beat, "payload": ...}}
        — the supervisor's raw liveness view."""
        now = time.monotonic()
        with self._sup_lock:
            return {eid: {"age": now - t, "payload": dict(payload)}
                    for eid, (t, payload) in self._leases.items()}

    def acked_partitions(self):
        """Partition ids acknowledged as fully consumed (stable copy)."""
        with self._sup_lock:
            return set(self._acked)

    def lease_epoch(self, executor_id):
        """The CURRENT minted epoch for ``executor_id`` (None when the
        identity never acquired one — legacy epoch-less beats)."""
        with self._sup_lock:
            return self._epochs.get(executor_id)

    def mint_epoch(self, executor_id):
        """Mint the next lease epoch for ``executor_id`` and make it
        current — every outstanding older epoch is fenced from this
        moment. The server-side half of ``Client.lease``; also callable
        in-process (the supervisor spawning a replacement replica
        fences the incumbent BEFORE the replacement's first beat).

        With a journal attached, the epoch is fsync'd durable BEFORE
        it becomes current or is returned: a crash landed anywhere
        after the journal write leaves the recovered floor >= every
        epoch any caller ever saw (the safe direction — a floor may
        exceed reality, never trail it)."""
        with self._sup_lock:
            epoch = self._epochs.get(executor_id, 0) + 1
            if self.journal is not None:
                # persist-before-publish: holding _sup_lock through
                # the fsync serializes mints against the journal, so
                # no later mint can return before an earlier one is
                # durable
                self.journal.record_epoch(executor_id, epoch)
            self._epochs[executor_id] = epoch
            self._awaiting_reannounce.discard(executor_id)
        logger.info("lease epoch %d minted for %r", epoch, executor_id)
        return epoch

    def mint_control_epoch(self):
        """Mint the next CONTROL epoch — the admin-plane fencing token
        (PR 19). A router taking over leadership mints one and stamps
        every admin RPC with it; replicas refuse writes below their
        observed floor (409), so a deposed leader's late writes land
        nowhere. Journal-backed like lease epochs: durable before
        returned, monotonic across server restarts by construction."""
        with self._sup_lock:
            epoch = self._control_epoch + 1
            if self.journal is not None:
                self.journal.record_control(epoch)
            self._control_epoch = epoch
        logger.info("control epoch %d minted", epoch)
        return epoch

    def control_epoch(self):
        """The highest minted control epoch (0 = never minted)."""
        with self._sup_lock:
            return self._control_epoch

    def recovering(self):
        """True while this server is a journal-seeded restart whose
        incumbents have not all re-announced and the recovery grace
        window is still open. Supervisor/autoscaler dead-lease
        classification gates on this: right after a restart the lease
        table is EMPTY by construction (replicas re-populate it via
        their next beats), and classifying that emptiness as fleet
        death would trigger a pointless mass-replacement storm."""
        with self._sup_lock:
            if not self._awaiting_reannounce:
                return False
            if self._recovery_deadline is None:
                return True  # start() not called yet — still cold
            if time.monotonic() >= self._recovery_deadline:
                # grace expired: whoever never re-announced really is
                # gone; let the supervisor/autoscaler see it
                self._awaiting_reannounce.clear()
                return False
            return True

    def drop_lease(self, identity):
        """Remove ``identity``'s lease (deliberate deregistration — a
        retired serving replica must vanish from ``serving_snapshot``
        rather than linger as an ever-aging corpse the autoscaler would
        keep counting). The identity's EPOCH is kept: a zombie beat
        from a stop RPC that never landed re-creates nothing — the
        retirer minted a fresh epoch first, so the zombie is answered
        FENCED and latches itself. Returns True when a lease was
        dropped."""
        with self._sup_lock:
            dropped = self._leases.pop(identity, None) is not None
        if dropped:
            logger.info("lease for %r dropped (deregistered)", identity)
        return dropped

    def set_cluster_width(self, width, target=None):
        """Publish this formation's width (and the job's configured
        target width) for the driver-side /metrics and /stats views —
        ``tfos_cluster_width`` / ``tfos_cluster_width_target``."""
        with self._sup_lock:
            self._cluster_width = None if width is None else int(width)
            if target is not None:
                self._cluster_width_target = int(target)

    def cluster_gauges(self):
        """{family: value} of the width gauges (only those set)."""
        with self._sup_lock:
            out = {}
            if self._cluster_width is not None:
                out["tfos_cluster_width"] = self._cluster_width
            if self._cluster_width_target is not None:
                out["tfos_cluster_width_target"] = \
                    self._cluster_width_target
            if self._control_epoch:
                out["tfos_control_epoch"] = self._control_epoch
            if self.journal is not None:
                out["tfos_control_recovery_pending"] = \
                    len(self._awaiting_reannounce)
            return out

    def serving_snapshot(self):
        """{replica_id: serving-replica view} from leases whose BEAT
        payload declares ``role: "serving"`` — the fleet plane
        (fleet.py): each view carries the lease age, the replica's
        advertised HTTP address, its model name, the live load gauges
        (``serving``: queue depth / slot occupancy / queue-wait EWMA /
        alive / draining, see ``DecodeEngine.load_stats``), and the
        beat-piggybacked engine registry snapshot (``metrics``). The
        FleetRouter's least-loaded dispatch and per-replica /metrics
        labels both read this; ``GET /stats`` exposes it as the
        ``serving`` key."""
        out = {}
        for eid, lease in self.lease_snapshot().items():
            payload = lease["payload"]
            if payload.get("role") != "serving":
                continue
            out[str(eid)] = {
                "age": round(lease["age"], 3),
                "addr": payload.get("addr"),
                "model": payload.get("model"),
                "epoch": payload.get("epoch"),
                "serving": payload.get("serving") or {},
                "metrics": payload.get("metrics"),
                # executor-hosted replicas (PR 13): where this replica
                # actually runs ({"executor": id, "pid": n}) — the
                # replica_id -> host join the autoscaler places by and
                # the router's replica_host info gauge renders; absent
                # for driver-local replicas
                "host": payload.get("host"),
            }
        return out

    def metrics_snapshot(self):
        """{executor_id: per-executor observability view} from the
        latest BEAT payloads: the beat-piggybacked MetricsRegistry
        snapshot (feed stages + counters), the train-step and
        feed-progress gauges, node state, and lease age. The raw
        material for ``cluster.metrics()`` (merged via
        ``tracing.cluster_rollup``) and the driver-side ``/metrics``
        exposition."""
        out = {}
        for eid, lease in self.lease_snapshot().items():
            payload = lease["payload"]
            out[eid] = {"metrics": payload.get("metrics"),
                        "train_step": payload.get("train_step"),
                        "feed_hb": payload.get("feed_hb"),
                        "state": payload.get("state"),
                        "age": round(lease["age"], 3)}
        return out

    def start(self, host=None, port=0):
        """Bind and serve in the background; returns (host, port).

        ``port`` (default ephemeral) lets a RESTARTED server rebind
        its predecessor's advertised port, so replicas reconnecting to
        the address they already hold find the new incarnation without
        re-discovery (PR 19 headless-fleet recovery)."""
        if host is None:
            from tensorflowonspark_tpu.util import get_ip_address
            host = get_ip_address()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Bind the wildcard so both loopback (local tests) and the routable
        # interface (real executors) can connect; advertise the routable host.
        self._sock.bind(("", int(port)))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        self.addr = (host, port)
        with self._sup_lock:
            if self._awaiting_reannounce:
                self._recovery_deadline = \
                    time.monotonic() + self._recovery_grace
        self._thread = threading.Thread(target=self._serve, name="reservation-server",
                                        daemon=True)
        self._thread.start()
        self._start_stats_http()
        logger.info("reservation server listening at %s (stats http %s)",
                    self.addr, self.stats_addr)
        return self.addr

    def _start_stats_http(self):
        """Tiny driver-side observability endpoint next to the TCP
        rendezvous port: ``GET /metrics`` renders the cluster's
        beat-piggybacked metrics in OpenMetrics text (per-executor
        ``executor``-labeled series — scrape the driver and the whole
        fleet is visible), ``GET /stats`` the same view as JSON.
        Best-effort: a bind failure logs and leaves ``stats_addr``
        None rather than failing cluster formation."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                from tensorflowonspark_tpu import goodput
                if self.path == "/metrics":
                    code, ctype = 200, tracing.OPENMETRICS_CONTENT_TYPE
                    # goodput plane: annotate per-executor step-time
                    # skew vs the fleet median so the exposition
                    # carries tfos_train_step_skew{executor=...}
                    body = tracing.render_cluster(
                        goodput.attach_step_skew(
                            server.metrics_snapshot()),
                        cluster_gauges=server.cluster_gauges()) \
                        .encode("utf-8")
                elif self.path == "/stats":
                    code, ctype = 200, "application/json"
                    stats = tracing.cluster_rollup(
                        goodput.attach_step_skew(
                            server.metrics_snapshot()))
                    # elastic resize: live width vs configured target
                    gauges = server.cluster_gauges()
                    stats["cluster"]["width"] = gauges.get(
                        "tfos_cluster_width")
                    stats["cluster"]["width_target"] = gauges.get(
                        "tfos_cluster_width_target")
                    # fleet plane: per-replica serving view (lease age,
                    # addr, load gauges) keyed by replica_id — the
                    # operator's "what is the router seeing" endpoint.
                    # The registry snapshot is dropped from this JSON
                    # view (it is /metrics' job, rendered per-replica)
                    stats["serving"] = {
                        rid: {k: v for k, v in view.items()
                              if k != "metrics"}
                        for rid, view in
                        server.serving_snapshot().items()}
                    body = json.dumps(stats).encode("utf-8")
                else:
                    code, ctype = 404, "application/json"
                    body = json.dumps(
                        {"error": "not found: %s" % self.path}) \
                        .encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet by default
                logger.debug("stats http: " + fmt, *args)

        try:
            httpd = ThreadingHTTPServer(("", 0), Handler)
        except OSError as e:
            logger.warning("driver stats endpoint failed to start: %s", e)
            with self._sup_lock:
                self._stats_httpd = None
            self.stats_addr = None
            return
        with self._sup_lock:
            self._stats_httpd = httpd
        self.stats_addr = (self.addr[0], httpd.server_address[1])
        # tfos: unjoined(stop() shuts the httpd down; serve_forever returns and the daemon exits)
        threading.Thread(target=httpd.serve_forever,
                         name="reservation-stats-http",
                         daemon=True).start()

    def _serve(self):
        while not self.done.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break  # listening socket closed by stop()
            # tfos: unjoined(one daemon per connection, bounded by recv_deadline; ends at socket close)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True, name="tfos-resv-conn").start()

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ms = MessageSocket(conn, recv_deadline=self.recv_deadline)
        try:
            while not self.done.is_set():
                msg = ms.receive()
                mtype = msg.get("type")
                if mtype == "REG":
                    self.reservations.add(msg["meta"])
                    ms.send({"type": "OK"})
                elif mtype == "QUERY":
                    ms.send({"type": "STATE", "done": self.reservations.done()})
                elif mtype == "QINFO":
                    ms.send({"type": "INFO", "meta": self.reservations.get(),
                             "done": self.reservations.done()})
                elif mtype == "LEASE":
                    eid = msg.get("executor_id")
                    ms.send({"type": "LEASE", "executor_id": eid,
                             "epoch": self.mint_epoch(eid)})
                elif mtype == "BEAT":
                    eid = msg.get("executor_id")
                    epoch = msg.get("epoch")
                    payload = msg.get("payload") or {}
                    with self._sup_lock:
                        self._beats_seen += 1
                        beats_seen = self._beats_seen
                        current = self._epochs.get(eid)
                        if current is None and epoch is not None:
                            # headless-fleet recovery (PR 19): a server
                            # that never minted for this identity (cold
                            # start, or journal deliberately moved
                            # aside) ADOPTS the replica's announced
                            # epoch as current — the replicas are the
                            # source of truth for their own leases. A
                            # journal-seeded restart never lands here:
                            # its floors cover every epoch ever minted,
                            # so `current` is the floor and a matching
                            # re-announce re-registers the SAME epoch.
                            self._epochs[eid] = int(epoch)
                            if self.journal is not None:
                                self.journal.record_epoch(eid, epoch)
                            current = int(epoch)
                            logger.info(
                                "adopted announced epoch %d for %r",
                                current, eid)
                        fenced = current is not None and epoch != current
                        if not fenced:
                            if epoch is not None:
                                # the lease view carries its epoch, so
                                # snapshots/routers can see which
                                # incarnation is current
                                payload = dict(payload, epoch=epoch)
                            self._leases[eid] = (time.monotonic(), payload)
                            self._awaiting_reannounce.discard(eid)
                    # chaos site (PR 19): kill_reservation_server=N
                    # crashes the server at the N-th BEAT, AFTER the
                    # lease-table write but BEFORE the reply — the
                    # SIGKILL-between-state-and-ack window the journal
                    # property test pins (the beater sees only a dead
                    # socket, exactly as a real kill looks)
                    if chaos.on_reservation_beat(beats_seen):
                        self.crash()
                        return  # no reply: the kill ate it
                    if fenced:
                        # the stale beat must NOT refresh the lease —
                        # the replacement's is the live one — and the
                        # beater must learn it is superseded
                        logger.warning(
                            "fencing stale beat from %r (epoch %r, "
                            "current %r)", eid, epoch, current)
                        ms.send({"type": "FENCED", "executor_id": eid,
                                 "epoch": current})
                    else:
                        ms.send({"type": "OK"})
                elif mtype == "ACK":
                    with self._sup_lock:
                        self._acked.add(msg.get("partition"))
                    ms.send({"type": "OK"})
                elif mtype == "ACKS":
                    with self._sup_lock:
                        acked = sorted(self._acked)
                    ms.send({"type": "ACKS", "partitions": acked})
                elif mtype == "STOP":
                    self.done.set()
                    self._close_listener()  # unblock _serve's accept()
                    ms.send({"type": "OK"})
                else:
                    ms.send({"type": "ERR", "error": "unknown type {!r}".format(mtype)})
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            ms.close()

    def await_reservations(self, timeout=DEFAULT_TIMEOUT, status=None):
        """Block until all N nodes registered; returns sorted cluster_info.

        ``status`` is an optional zero-arg callable polled for early-abort
        (the reference passes the SparkContext to notice cancelled jobs).
        """
        deadline = time.monotonic() + timeout
        while not self.reservations.done():
            if status is not None:
                status()
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError_(
                    "timed out waiting for {} node registrations".format(
                        self.reservations.remaining()))
            try:
                self.reservations.wait(min(left, 1.0))
            except TimeoutError_:
                continue
        return sort_cluster_info(self.reservations.get())

    def _close_listener(self):
        if self._sock is not None:
            # shutdown() BEFORE close(): on Linux, close() alone does
            # not wake a thread blocked in accept() — the serve thread
            # would sit there until stop()'s 5s join timeout expired,
            # a teardown tax every cluster/fleet spin paid. shutdown()
            # on a listening socket raises ENOTCONN on some platforms
            # (harmless) but reliably unblocks accept() here.
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    def stop(self):
        self.done.set()
        self._close_listener()
        with self._sup_lock:
            httpd, self._stats_httpd = self._stats_httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.journal is not None:
            self.journal.close()

    def crash(self):
        """Chaos only (PR 19): die the way a SIGKILLed driver process
        looks from outside — listener gone mid-conversation, no STOP
        handshake, no replies to in-flight messages, no thread joins.
        Every lease, epoch, and ack in MEMORY is lost exactly as a
        real kill loses them; only the journal's fsync'd floors
        survive, which is the entire point. A restarted server
        (``journal=`` the same path) re-seeds its floors from disk and
        re-learns the live leases from the replicas' re-announced
        beats."""
        logger.error("reservation server CRASHED (chaos kill) — "
                     "in-memory leases/epochs lost, journal floors %s",
                     "retained" if self.journal is not None
                     else "ABSENT (no journal)")
        self.done.set()
        self._close_listener()
        with self._sup_lock:
            httpd, self._stats_httpd = self._stats_httpd, None
        if httpd is not None:
            try:
                httpd.server_close()
            except OSError:
                pass
            # shutdown() blocks until the serve loop notices; crash()
            # can be called from a handler thread, so park it off-path
            # tfos: unjoined(crash emulation — a killed process joins nothing)
            threading.Thread(target=httpd.shutdown,
                             daemon=True,
                             name="tfos-resv-crash").start()
        if self.journal is not None:
            # a killed process's fd is simply gone; everything durable
            # is already on disk (fsync-before-reply)
            self.journal.close()


class Client(object):
    """Executor-side client of the rendezvous server.

    Reference: ``reservation.Client`` — one persistent connection; register,
    poll until the barrier opens, fetch the full node list.
    """

    def __init__(self, server_addr, connect_timeout=30):
        self.server_addr = tuple(server_addr)
        sock = socket.create_connection(self.server_addr,
                                        timeout=connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._ms = MessageSocket(sock)
        self._lock = threading.Lock()

    def _call(self, msg):
        with self._lock:
            self._ms.send(msg)
            return self._ms.receive()

    def abort(self):
        """Out-of-band close: shut the socket down WITHOUT taking the
        call lock, so a thread wedged inside :meth:`_call` against a
        dead server fails out with ``ConnectionError``/``OSError``
        immediately instead of holding its caller hostage. The bounded
        close path driver teardown uses after a reservation-server
        crash (PR 19) — ``close()`` itself is also lock-free, but
        ``abort`` names the intent at call sites."""
        self._ms.close()

    def register(self, meta):
        resp = self._call({"type": "REG", "meta": meta})
        if resp.get("type") != "OK":
            raise RuntimeError("registration rejected: {!r}".format(resp))

    def get_reservations(self):
        return sort_cluster_info(self._call({"type": "QINFO"})["meta"])

    def await_reservations(self, timeout=DEFAULT_TIMEOUT, poll_interval=0.1):
        """Poll until all nodes registered; returns sorted cluster_info."""
        deadline = time.monotonic() + timeout
        while True:
            # Cheap QUERY while waiting (O(1) reply); one QINFO at the end —
            # N clients polling full metas would be O(N^2) on the driver.
            resp = self._call({"type": "QUERY"})
            if resp.get("done"):
                return sort_cluster_info(self._call({"type": "QINFO"})["meta"])
            if time.monotonic() > deadline:
                raise TimeoutError_("timed out awaiting cluster reservations")
            time.sleep(poll_interval)
            # back off gently to keep the driver's accept loop unloaded
            poll_interval = min(poll_interval * 1.5, 2.0)

    def lease(self, executor_id):
        """Acquire a fresh lease epoch for ``executor_id`` — the
        fencing token every subsequent :meth:`beat` must carry. Minting
        SUPERSEDES any prior holder of the identity: its next beat is
        answered FENCED (see :class:`Fenced`). Serving replicas acquire
        one before their first beat; a fenced replica re-registers
        through here (a deliberate act, never an automatic retry)."""
        # same chaos labels as beat(): a partition scoped to this
        # identity's reservation link must catch its LEASE exchanges
        # too — a fully partitioned replica cannot mint an epoch
        # through a supposedly-down link
        self._ms.net_src = executor_id
        self._ms.net_dst = "reservation"
        resp = self._call({"type": "LEASE", "executor_id": executor_id})
        if resp.get("type") != "LEASE":
            raise RuntimeError("lease rejected: {!r}".format(resp))
        return int(resp["epoch"])

    def beat(self, executor_id, payload=None, epoch=None):
        """Refresh this executor's heartbeat lease (supervision plane).
        ``payload`` is a small JSON-able status dict (trainer liveness,
        feed progress, train step) the Supervisor classifies. ``epoch``
        (from :meth:`lease`) is the fencing token: a beat carrying a
        stale one raises :class:`Fenced` — NON-retriable; the caller
        must stop acting as the identity's serving incarnation."""
        # label the exchange for the chaos network fault plane: a
        # net_partition=<id>:reservation spec catches exactly this
        # identity's beats
        self._ms.net_src = executor_id
        self._ms.net_dst = "reservation"
        msg = {"type": "BEAT", "executor_id": executor_id,
               "payload": payload or {}}
        if epoch is not None:
            msg["epoch"] = int(epoch)
        resp = self._call(msg)
        if resp.get("type") == "FENCED":
            raise Fenced(
                "beat fenced: {!r} epoch {} superseded (current {})"
                .format(executor_id, epoch, resp.get("epoch")),
                epoch=resp.get("epoch"))
        if resp.get("type") != "OK":
            raise RuntimeError("beat rejected: {!r}".format(resp))

    def ack(self, partition):
        """Record feed partition ``partition`` as fully consumed; a
        supervised restart skips acked partitions on replay."""
        resp = self._call({"type": "ACK", "partition": partition})
        if resp.get("type") != "OK":
            raise RuntimeError("ack rejected: {!r}".format(resp))

    def acked(self):
        """Partitions acknowledged so far (the driver-side view a trainer
        or test can poll to observe the exactly-once boundary — e.g.
        'my step N's partition has been recorded consumed')."""
        resp = self._call({"type": "ACKS"})
        if resp.get("type") != "ACKS":
            raise RuntimeError("acks query rejected: {!r}".format(resp))
        return set(resp.get("partitions") or ())

    def request_stop(self):
        try:
            self._call({"type": "STOP"})
        except (ConnectionError, OSError):
            pass

    def close(self):
        self._ms.close()


def sort_cluster_info(meta_list):
    """Deterministic node ordering: by executor_id (every view identical).

    The sorted list is the framework's ``cluster_spec`` analog: index in the
    sorted list == JAX process index; entry 0's host/port is the
    coordination-service address (SURVEY.md §2.4 plane 1).
    """
    return sorted(meta_list, key=lambda m: m.get("executor_id", 0))
