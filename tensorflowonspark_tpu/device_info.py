"""Accelerator discovery for executor/feeder processes — jax-free.

Reference: ``tensorflowonspark/gpu_info.py`` (SURVEY.md §2 "GPU
allocator"): parse ``nvidia-smi``, pick free GPUs, set
``CUDA_VISIBLE_DEVICES``, retry the multi-executor grab race. On TPU
hosts the race does not exist — chips are bound to the host and owned by
whichever single process initializes the runtime — so this module only
*discovers and describes*; binding is the trainer process's act of
initializing jax (SURVEY.md §5 "Race detection").

Must stay importable (and cheap) in processes that never touch jax.
"""

import glob
import logging
import os

logger = logging.getLogger(__name__)

MAX_RETRIES = 3  # kept for API parity with gpu_info; unused on TPU


def _accel_device_files():
    """TPU device nodes exposed by the VM image."""
    return sorted(glob.glob("/dev/accel*")) + sorted(glob.glob("/dev/vfio/*"))


def is_tpu_available():
    """True if this host exposes TPU chips (device files or env posture)."""
    if _accel_device_files():
        return True
    return bool(os.environ.get("TPU_WORKER_ID")
                or os.environ.get("TPU_SKIP_MDS_QUERY")
                or os.environ.get("JAX_PLATFORMS", "").startswith(("tpu",
                                                                   "axon")))


# reference-name alias (gpu_info.is_gpu_available gates the same decision)
is_gpu_available = is_tpu_available


def get_devices(num_devices=None):
    """Describe local accelerator slots without initializing a runtime.

    Reference: ``gpu_info.get_gpus(num_gpus)`` returned a CSV index string
    for CUDA_VISIBLE_DEVICES. The TPU analog returns the device-file list
    (or a 1-slot placeholder when only env posture reveals the TPU); the
    trainer does NOT need it to bind — it exists for logging/diagnostics
    and for populating reservation metadata.
    """
    files = _accel_device_files()
    if not files and is_tpu_available():
        files = ["tpu:0"]
    if num_devices is not None and len(files) < num_devices:
        raise RuntimeError(
            "requested {} local TPU devices, found {}".format(
                num_devices, len(files)))
    return files


def topology_env():
    """The libtpu topology variables present in this environment, if any
    (multi-host pods publish these; useful in reservation metadata)."""
    keys = ("TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES", "TPU_CHIPS_PER_HOST",
            "TPU_HOST_BOUNDS", "TPU_PROCESS_BOUNDS", "TPU_VISIBLE_CHIPS",
            "TPU_ACCELERATOR_TYPE")
    return {k: os.environ[k] for k in keys if k in os.environ}
