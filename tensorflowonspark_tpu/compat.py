"""Compatibility shims — the reference's ``compat.py`` surface.

Reference: ``tensorflowonspark/compat.py`` (SURVEY.md §2 "TF1/TF2 compat
shims"): version bridges the reference needed between TF eras. The
TPU-native equivalents are mostly trivial, kept so reference-style user
code ports mechanically.
"""

from tensorflowonspark_tpu.device_info import is_tpu_available  # noqa: F401

# reference name
is_gpu_available = is_tpu_available


def shard_map(f, **kwargs):
    """``jax.shard_map`` across the import-path move.

    Newer jax exposes ``jax.shard_map`` (kwarg ``check_vma``); older
    releases only have ``jax.experimental.shard_map.shard_map`` (same
    surface, the kwarg was still called ``check_rep``). The parallel
    modules route through this shim so the framework runs on both sides
    of the rename without scattering version probes.
    """
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


def export_saved_model(export_dir, apply_fn, variables, is_chief,
                       signature=None):
    """Chief-only export (reference: ``compat.export_saved_model(model,
    dir, is_chief)`` — non-chief calls are no-ops)."""
    if not is_chief:
        return
    from tensorflowonspark_tpu import export

    export.save_model(export_dir, apply_fn, variables, signature)


def disable_auto_shard(options=None):
    """No-op: the reference disabled tf.data auto-sharding for queue-fed
    datasets; our feed plane shards at the queue level by construction."""
    return options
