"""Python binding for the native shared-memory feed ring (native/shm_ring.cpp).

The fast path of the feed plane: the manager queue (manager.py) remains
the control channel, while bulk record chunks can ride this SPSC ring —
one mmap'd copy instead of a pickled TCP round trip through a manager
proxy thread per chunk. Enabled per cluster with
``TFOS_FEED_TRANSPORT=shm`` (see node.py); the queue path stays the
default and the semantics (EndPartition/EndFeed markers, join-on-consume,
state aborts) are identical.

The .so builds on first use with the toolchain baked into the image
(g++); the build is cached next to this file. Everything degrades
gracefully: ``available()`` is False where g++ or POSIX shm is missing.
"""

import ctypes
import logging
import os
import pickle
import subprocess
import threading

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "shm_ring.cpp")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "_libshmring.so")
_lib = None
_lib_lock = threading.Lock()


def _build():
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO + ".tmp",
           _SRC, "-lrt", "-pthread"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(_SO + ".tmp", _SO)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC) and
                os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_open.restype = ctypes.c_void_p
        lib.shmring_open.argtypes = [ctypes.c_char_p]
        lib.shmring_write.restype = ctypes.c_int
        lib.shmring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.shmring_peek_len.restype = ctypes.c_int64
        lib.shmring_peek_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shmring_read.restype = ctypes.c_int64
        lib.shmring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_int]
        lib.shmring_pending.restype = ctypes.c_uint64
        lib.shmring_pending.argtypes = [ctypes.c_void_p]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        lib.shmring_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
        return lib


def available():
    """True if the native ring can be built/loaded on this host."""
    try:
        _load()
        return True
    except Exception as e:  # noqa: BLE001
        logger.info("native shm ring unavailable: %s", e)
        return False


class ShmRing(object):
    """One SPSC byte-message ring. create() on the producer-side host
    process; open() from the consumer. Not thread-safe per side."""

    DEFAULT_CAPACITY = 64 * 1024 * 1024

    def __init__(self, handle, name, owner):
        self._h = handle
        self.name = name
        self._owner = owner

    @classmethod
    def create(cls, name, capacity=DEFAULT_CAPACITY):
        lib = _load()
        handle = lib.shmring_create(name.encode(), capacity)
        if not handle:
            raise OSError("shmring_create failed for {!r}".format(name))
        return cls(handle, name, owner=True)

    @classmethod
    def open(cls, name):
        lib = _load()
        handle = lib.shmring_open(name.encode())
        if not handle:
            raise OSError("shmring_open failed for {!r}".format(name))
        return cls(handle, name, owner=False)

    def write(self, data, timeout=None):
        """Write one message; raises TimeoutError/ValueError."""
        rc = _load().shmring_write(
            self._h, bytes(data), len(data),
            -1 if timeout is None else int(timeout * 1000))
        if rc == -1:
            raise TimeoutError("shm ring full")
        if rc == -2:
            raise ValueError("message larger than ring capacity")

    def read(self, timeout=None):
        """Read one message; returns bytes or None on timeout."""
        lib = _load()
        t = -1 if timeout is None else int(timeout * 1000)
        n = lib.shmring_peek_len(self._h, t)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = lib.shmring_read(self._h, buf, int(n), t)
        if got < 0:
            return None
        return buf.raw[:got]

    def pending(self):
        """Unconsumed bytes (0 == fully drained)."""
        return int(_load().shmring_pending(self._h))

    def write_obj(self, obj, timeout=None):
        self.write(pickle.dumps(obj, protocol=5), timeout)

    def read_obj(self, timeout=None):
        data = self.read(timeout)
        return None if data is None else pickle.loads(data)

    def close(self):
        if self._h:
            _load().shmring_close(self._h)
            self._h = None

    def unlink(self):
        try:
            _load().shmring_unlink(self.name.encode())
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
