"""Python binding for the native shared-memory feed ring (native/shm_ring.cpp).

The fast path of the feed plane: the manager queue (manager.py) remains
the control channel, while bulk record chunks ride this SPSC ring — a
gather-memcpy into one mmap'd region instead of pickled TCP round trips
through a manager proxy per chunk. The v2 ring blocks on futexes (no
polling — critical on single-core hosts where a spinning consumer starves
the producer) and keeps messages contiguous, so the consumer can decode
columnar frames (frames.py) as zero-copy views into the mapping.

Enabled per cluster with ``TFOS_FEED_TRANSPORT=shm`` (the default when the
broker is local and the ring builds — see node.py); semantics
(EndPartition/EndFeed markers, drain-on-consume, state aborts) are
identical to the queue path.

The .so builds on first use with the toolchain baked into the image
(g++); the build is cached next to this file. Everything degrades
gracefully: ``available()`` is False where g++ or POSIX shm is missing.
"""

import ctypes
import logging
import os
import pickle
import subprocess
import threading

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "shm_ring.cpp")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "_libshmring.so")
_lib = None
_lib_lock = threading.Lock()

_from_memory = ctypes.pythonapi.PyMemoryView_FromMemory
_from_memory.restype = ctypes.py_object
_from_memory.argtypes = (ctypes.c_void_p, ctypes.c_ssize_t, ctypes.c_int)
_PyBUF_READ = 0x100


def _build():
    # per-pid temp: concurrent executor processes all lazily build; a
    # shared .tmp would tear and the mtime guard would then pin the torn
    # .so forever. os.replace of complete files is atomic either way.
    tmp = "{}.{}.tmp".format(_SO, os.getpid())
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
           _SRC, "-lrt", "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC) and
                os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_open.restype = ctypes.c_void_p
        lib.shmring_open.argtypes = [ctypes.c_char_p]
        lib.shmring_write.restype = ctypes.c_int
        lib.shmring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.shmring_write_gather.restype = ctypes.c_int
        lib.shmring_write_gather.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int]
        lib.shmring_read_ptr.restype = ctypes.c_void_p
        lib.shmring_read_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_uint64)]
        lib.shmring_advance.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shmring_peek_len.restype = ctypes.c_int64
        lib.shmring_peek_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shmring_read.restype = ctypes.c_int64
        lib.shmring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_int]
        lib.shmring_pending.restype = ctypes.c_uint64
        lib.shmring_pending.argtypes = [ctypes.c_void_p]
        lib.shmring_wait_drained.restype = ctypes.c_int
        lib.shmring_wait_drained.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        lib.shmring_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
        return lib


def sweep_stale(executor_id=None, pattern=None):
    """Unlink rings whose creating process is dead; returns names removed.

    SIGKILL is the one exit the atexit/shutdown cleanups cannot cover
    (VERDICT r4 task 7): a feeder killed -9 leaves its segment behind,
    and since ring names embed the cluster id, a *new* cluster would
    never reuse (and thus never clear) the old name. Ring names embed
    the creator pid (``/tfos-<id>-<eid>.<pid>``, node.py) precisely so
    this sweep can test liveness: dead pid -> stale segment. Scoped to
    one executor slot at node bootstrap (never touching a concurrent
    cluster's live rings, whose pids are alive); unscoped from the
    engine driver's stop() on hosts it owns. pid-less legacy names are
    left alone — liveness is unknowable for them.

    ``pattern`` (a ``/dev/shm`` glob) narrows the sweep to one ring
    family instead of one executor slot — the serving bootstrap reaps
    only KV-ship rings (``/dev/shm/tfos-kvship-*.*``, PR 17) this way,
    leaving a co-hosted training cluster's feed rings alone even when
    their liveness proof would pass.
    """
    import glob
    import re

    pat = pattern if pattern is not None else (
        "/dev/shm/tfos-*-{}.*".format(executor_id)
        if executor_id is not None else "/dev/shm/tfos-*.*")
    removed = []
    for path in glob.glob(pat):
        base = os.path.basename(path)
        m = re.match(r".+\.(\d+)$", base)
        if not m:
            continue
        pid = int(m.group(1))
        try:
            os.kill(pid, 0)
            continue  # creator alive: the ring is (or may be) live
        except ProcessLookupError:
            pass
        except OSError:
            continue  # EPERM etc.: can't prove death, leave it
        try:
            _load().shmring_unlink(("/" + base).encode())
            removed.append("/" + base)
            logger.info("swept stale shm ring %s (dead pid %d)", base, pid)
        except Exception:  # noqa: BLE001 - best effort
            pass
    return removed


def available():
    """True if the native ring can be built/loaded on this host."""
    try:
        _load()
        return True
    except Exception as e:  # noqa: BLE001
        logger.info("native shm ring unavailable: %s", e)
        return False


#: below this ring size the transport is not worth it (one 256-image
#: uint8 224px frame is ~38MB and messages are capped at capacity/2)
MIN_USEFUL_CAPACITY = 64 * 1024 * 1024


def default_capacity():
    """Ring data-region size: enough runway for a few full device batches
    (a 256-image uint8 224px frame is ~38MB), env-tunable and bounded by
    half of /dev/shm's free space so a ring never fights the host for it.

    Returns 0 when /dev/shm can't fit a useful ring — callers must fall
    back to the queue transport (tmpfs pages materialize lazily, so an
    oversized ring would SIGBUS the producer mid-feed, not fail create).
    """
    want = 256 * 1024 * 1024
    env = os.environ.get("TFOS_SHM_CAPACITY")
    if env:
        want = int(env)
    try:
        st = os.statvfs("/dev/shm")
        free_half = st.f_bavail * st.f_frsize // 2
        if want > free_half:
            # The env override is clamped too: tmpfs pages materialize
            # lazily, so an oversized ring SIGBUSes the producer mid-feed
            # instead of failing create — honoring the override verbatim
            # would re-open exactly that hazard.
            if env:
                logger.warning(
                    "TFOS_SHM_CAPACITY=%s exceeds half of /dev/shm free "
                    "space; clamping to %d", env, free_half)
            want = free_half
    except OSError:
        pass
    # The env override does not bypass the uselessly-small floor either:
    # a clamped-down ring whose max message (capacity/2) can't hold one
    # record would fail mid-feed, whereas 0 makes node.py fall back to
    # the queue transport cleanly.
    return want if want >= MIN_USEFUL_CAPACITY else 0


#: capacity of a co-hosted KV-ship ring (PR 17 disaggregation):
#: shipments are a few blocks of int8 codes + scales — megabytes, not
#: the feed plane's 38MB image frames — so a small EXPLICIT capacity
#: beats :func:`default_capacity`'s feed-sized floor. ``create()``
#: honors explicit capacities below MIN_USEFUL_CAPACITY by design:
#: that floor guards the feed transport's fallback decision only.
KVSHIP_CAPACITY = 16 * 1024 * 1024


def kvship_ring_name(src_replica, dst_replica):
    """Canonical shm segment name of the src->dst KV-ship ring.

    The PREFILL side creates it (ShmRing's producer-side convention),
    and the name embeds the creator pid exactly like the feed rings
    (``/tfos-...<name>.<pid>``) so :func:`sweep_stale` can reap rings a
    SIGKILLed prefill worker left behind. Replica ids are sanitized to
    the shm-name alphabet (no dots: the pid suffix must stay the only
    ``.``-delimited field, or the sweep's liveness regex misparses)."""
    def _safe(s):
        return "".join(ch if ch.isalnum() or ch in "-_" else "-"
                       for ch in str(s))
    return "/tfos-kvship-{}-{}.{}".format(
        _safe(src_replica), _safe(dst_replica), os.getpid())


class ShmRing(object):
    """One SPSC byte-message ring. create() on the producer-side host
    process; open() from the consumer. Not thread-safe per side."""

    DEFAULT_CAPACITY = 64 * 1024 * 1024

    def __init__(self, handle, name, owner):
        self._h = handle
        self.name = name
        self._owner = owner

    @classmethod
    def create(cls, name, capacity=None):
        lib = _load()
        capacity = capacity or default_capacity()
        if not capacity:
            raise OSError("/dev/shm too small for a useful ring "
                          "(need {}MB free)".format(
                              2 * MIN_USEFUL_CAPACITY // 2 ** 20))
        handle = lib.shmring_create(name.encode(), capacity)
        if not handle:
            raise OSError("shmring_create failed for {!r}".format(name))
        return cls(handle, name, owner=True)

    @classmethod
    def open(cls, name):
        lib = _load()
        handle = lib.shmring_open(name.encode())
        if not handle:
            raise OSError("shmring_open failed for {!r}".format(name))
        return cls(handle, name, owner=False)

    # -- raw message API ---------------------------------------------------

    def write(self, data, timeout=None):
        """Write one message; raises TimeoutError/ValueError."""
        rc = _load().shmring_write(
            self._h, bytes(data), len(data),
            -1 if timeout is None else int(timeout * 1000))
        if rc == -1:
            raise TimeoutError("shm ring full")
        if rc == -2:
            raise ValueError("message larger than ring capacity")

    def write_buffers(self, buffers, timeout=None):
        """One message gathered from several byte-like buffers (no
        caller-side concat; raw array memory goes straight to the mmap)."""
        import numpy as np

        n = len(buffers)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        holds = []  # keep buffer owners alive across the call
        for i, b in enumerate(buffers):
            if isinstance(b, bytes):
                ptrs[i] = ctypes.cast(b, ctypes.c_void_p)
                lens[i] = len(b)
                holds.append(b)
                continue
            # numpy arrays and contiguous byte-likes: zero-copy address
            a = b if isinstance(b, np.ndarray) else \
                np.frombuffer(b, dtype=np.uint8)
            a = np.ascontiguousarray(a)
            ptrs[i] = a.ctypes.data
            lens[i] = a.nbytes
            holds.append(a)
        rc = _load().shmring_write_gather(
            self._h, ptrs, lens, n,
            -1 if timeout is None else int(timeout * 1000))
        del holds
        if rc == -1:
            raise TimeoutError("shm ring full")
        if rc == -2:
            raise ValueError("message larger than ring capacity")

    def read(self, timeout=None):
        """Read one message; returns bytes or None on timeout."""
        lib = _load()
        t = -1 if timeout is None else int(timeout * 1000)
        out_len = ctypes.c_uint64()
        ptr = lib.shmring_read_ptr(self._h, t, ctypes.byref(out_len))
        if not ptr:
            return None
        data = ctypes.string_at(ptr, out_len.value)
        lib.shmring_advance(self._h, out_len.value)
        return data

    def read_view(self, timeout=None):
        """(memoryview, release) of the next message, zero copy.

        The view addresses the ring mapping directly; call ``release()``
        exactly once when done to free the slot (until then the producer
        can't reclaim the space).

        SEQUENTIAL-CONSUMPTION CONTRACT: at most one outstanding view.
        The read position is the consumer tail, which only ``release``
        advances — a second ``read_view`` before releasing the first
        returns the SAME message again (and releasing both then
        over-advances the tail, desyncing the stream). DataFeed upholds
        this by unpinning every held slot before each blocking read.
        """
        lib = _load()
        t = -1 if timeout is None else int(timeout * 1000)
        out_len = ctypes.c_uint64()
        ptr = lib.shmring_read_ptr(self._h, t, ctypes.byref(out_len))
        if not ptr:
            return None, None
        view = _from_memory(ptr, out_len.value, _PyBUF_READ)
        n = out_len.value
        done = [False]  # one-shot: a double release would advance the
        # tail past an unconsumed message and desync the stream

        def release(_lib=lib, _h=self._h, _n=n, _done=done):
            if _done[0]:
                return
            _done[0] = True
            _lib.shmring_advance(_h, _n)

        return view, release

    def pending(self):
        """Unconsumed bytes (0 == fully drained)."""
        return int(_load().shmring_pending(self._h))

    def wait_drained(self, timeout=None):
        """Block until the consumer drained everything; True if drained.

        Futex-sleeps on the consumer's advance counter — the feeder's
        partition join wakes the instant the trainer releases the last
        message, instead of on a poll tick."""
        return bool(_load().shmring_wait_drained(
            self._h, -1 if timeout is None else int(timeout * 1000)))

    # -- object / frame API ------------------------------------------------

    def write_obj(self, obj, timeout=None):
        """Frame-encode ``obj`` (frames.py) and write it.

        ColumnarChunks move as raw column bytes; other objects pickle into
        the frame header.
        """
        from tensorflowonspark_tpu import frames
        self.write_buffers(frames.encode(obj), timeout)

    def read_obj(self, timeout=None):
        """Read one frame → object; None on timeout.

        ColumnarChunk columns are copied out of the ring (one memcpy) so
        the slot frees immediately and the result owns its memory. A
        coalesced multi-object frame (frames.encode_multi) comes back as
        a FrameList with every chunk materialized the same way.

        This is the copying legacy path (probes, drains, tools); the
        trainer's DataFeed consumes via read_view + a staging gather
        instead, releasing the slot only after the single copy out.
        """
        from tensorflowonspark_tpu import frames
        view, release = self.read_view(timeout)
        if view is None:
            return None
        try:
            obj = frames.decode(view)
            objs = obj if isinstance(obj, frames.FrameList) else (obj,)
            for o in objs:
                if isinstance(o, frames.ColumnarChunk):
                    o.materialize()
            return obj
        finally:
            release()

    def close(self):
        if self._h:
            _load().shmring_close(self._h)
            self._h = None

    def unlink(self):
        try:
            _load().shmring_unlink(self.name.encode())
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
