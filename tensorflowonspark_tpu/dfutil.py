"""DataFrame ⇄ TFRecord conversion utilities.

Reference: ``tensorflowonspark/dfutil.py`` (SURVEY.md §2 "TFRecord
interop"): ``saveAsTFRecords`` / ``loadTFRecords`` / ``infer_schema`` /
``toTFExample`` / ``fromTFExample``. The reference delegated the file
format to the third-party tensorflow-hadoop JAR; here the codec is
first-party (:mod:`tensorflowonspark_tpu.tfrecord`) and the files are
written/read directly by executor tasks in the Hadoop ``part-*`` layout.
"""

import os

from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.engine.dataframe import DataFrame


def toTFExample(schema):
    """Returns rows -> serialized Example bytes iterator transform.

    Reference: ``dfutil.toTFExample(dtypes)`` used per-partition via
    ``df.rdd.mapPartitions``.
    """
    schema = list(schema)

    def _convert(iterator):
        for row in iterator:
            features = {}
            for name, dtype in schema:
                v = row[name]
                if dtype == "string":
                    v = [v.encode("utf-8") if isinstance(v, str) else bytes(v)]
                elif dtype == "binary":
                    v = [bytes(v)]
                elif dtype == "int64":
                    v = [int(v)]
                elif dtype == "float32":
                    v = [float(v)]
                elif dtype.startswith("array<"):
                    inner = dtype[6:-1]
                    if inner == "int64":
                        v = [int(x) for x in v]
                    elif inner == "float32":
                        v = [float(x) for x in v]
                    else:  # array<string> / array<binary>
                        v = [x.encode("utf-8") if isinstance(x, str)
                             else bytes(x) for x in v]
                else:
                    raise TypeError("unsupported dtype {}".format(dtype))
                features[name] = v
            yield tfrecord.encode_example(features)

    return _convert


def fromTFExample(schema=None, binary_features=()):
    """Returns serialized-Example -> row-dict iterator transform.

    Reference: ``dfutil.fromTFExample``. ``binary_features`` lists
    bytes_list columns to keep as raw bytes (others decode utf-8, matching
    the reference's string-by-default behavior).
    """
    binary = set(binary_features)
    schema = list(schema) if schema else None
    smap = dict(schema) if schema else None

    def _convert(iterator):
        for data in iterator:
            parsed = tfrecord.parse_example(bytes(data))
            row = {}
            for name, (kind, values) in parsed.items():
                if kind == "bytes":
                    if name not in binary and (smap is None or
                                               "binary" not in
                                               smap.get(name, "")):
                        values = [v.decode("utf-8") for v in values]
                elif kind == "float":
                    values = [float(v) for v in values]
                elif kind == "int64":
                    values = [int(v) for v in values]
                if smap is not None:
                    dtype = smap.get(name, "")
                    if dtype.startswith("array<"):
                        row[name] = values
                    else:
                        if len(values) > 1:
                            raise ValueError(
                                "feature {!r} inferred as scalar {} but a "
                                "record holds {} values — variable-length "
                                "features need an array<> dtype (pass an "
                                "explicit schema)".format(
                                    name, dtype, len(values)))
                        row[name] = values[0] if values else None
                else:
                    row[name] = values[0] if len(values) == 1 else values
            if smap is not None:
                # Example features are optional per record: keep rows
                # rectangular so select()/re-save never KeyError.
                for cname, cdtype in smap.items():
                    if cname not in row:
                        row[cname] = [] if cdtype.startswith("array<") \
                            else None
            yield row

    return _convert


def infer_schema(example_bytes, binary_features=()):
    """First serialized Example -> [(name, dtype)] (sorted).

    Reference: ``dfutil.infer_schema`` on the first record. Multi-value
    features map to array<> dtypes; single-value to scalars (so fixed-size
    vectors round-trip as arrays).
    """
    parsed = tfrecord.parse_example(bytes(example_bytes))
    schema = []
    for name in sorted(parsed):
        kind, values = parsed[name]
        if kind == "bytes":
            base = "binary" if name in binary_features else "string"
        elif kind == "float":
            base = "float32"
        elif kind == "int64":
            base = "int64"
        else:
            base = "float32"
        if len(values) > 1:
            base = "array<{}>".format(base)
        schema.append((name, base))
    return schema


def saveAsTFRecords(df, output_dir):
    """Write a DataFrame as ``part-NNNNN`` TFRecord files.

    Reference: ``dfutil.saveAsTFRecords(df, output_dir)`` (which went
    through ``saveAsNewAPIHadoopFile``). Fails if output_dir exists, like
    Hadoop output committers do.
    """
    from tensorflowonspark_tpu import fs

    output_dir = fs.require_local(output_dir, "saveAsTFRecords")
    os.makedirs(output_dir, exist_ok=False)
    schema = df.schema
    serialized = df.rdd.mapPartitions(toTFExample(schema))

    def _write(index, iterator):
        path = os.path.join(output_dir, "part-%05d" % index)
        with tfrecord.TFRecordWriter(path) as w:
            count = 0
            for record in iterator:
                w.write(record)
                count += 1
        yield count

    return sum(serialized.mapPartitionsWithIndex(_write).collect())


def loadTFRecords(sc, input_dir, binary_features=(), num_partitions=None):
    """Load a TFRecord directory as a DataFrame.

    Reference: ``dfutil.loadTFRecords`` — reads the first record to infer
    the schema, then parses every file. One partition per part file by
    default (the Hadoop-split analog).
    """
    files = tfrecord.list_tfrecord_files(input_dir)
    if not files:
        raise FileNotFoundError("no part-* TFRecord files in " + input_dir)
    # Hadoop committers routinely write empty part files for empty
    # partitions: infer from the first file that actually has a record.
    first = None
    for path in files:
        # first_record: lazy single-record read — the native iterator
        # would CRC-scan the entire shard just to infer the schema
        first = tfrecord.first_record(path)
        if first is not None:
            break
    if first is None:
        raise ValueError("all part-* files in {} are empty".format(input_dir))
    schema = infer_schema(first, binary_features)

    file_rdd = sc.parallelize(files, num_partitions or len(files))
    conv = fromTFExample(schema, binary_features)

    def _read(iterator):
        for path in iterator:
            for row in conv(tfrecord.tfrecord_iterator(path)):
                yield row

    return DataFrame(file_rdd.mapPartitions(_read), schema)
