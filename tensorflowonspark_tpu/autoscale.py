"""SLO-driven autoscaler for the serving fleet (PR 13).

Capacity stops being a constructor argument: this module closes the
loop between the SLO signals the fleet already publishes (per-replica
``queue_wait_ewma_s``, TTFT p99 histograms, slot/KV occupancy — all
riding the serving BEAT leases PR 6 built) and the fleet's width. A
driver-side control loop reads the reservation server's serving
snapshot, runs a PURE decision function, and drives
``ServingFleet.spawn_replica`` / ``retire_replica`` /
``replace_replica``:

- **Scale-up, fast** — any live replica's queue-wait EWMA past the
  SLO, TTFT p99 past its target, or slot saturation with a standing
  queue is a BREACH; one replica is added per ``up_cooldown_s`` until
  ``max_replicas`` (hysteresis: breaches scale quickly, but never in a
  tight loop). Placement is evidence-gated the way PR 7's regrow probe
  is: scale-up happens only onto capacity that EXISTS
  (``ServingFleet.free_executor``); no free executor means a logged
  ``scale_up_blocked`` decision, not an invented replica.
- **Scale-down, slow** — sustained idleness (no queue anywhere, mean
  occupancy under the low watermark) retires the least-loaded replica
  through the zero-loss quiesce -> drain -> deregister path
  (``retire_replica`` — ``rolling_drain``'s contract), gated by the
  LONG ``down_cooldown_s`` measured from the last scale in EITHER
  direction, so a burst's trailing edge cannot flap the fleet.
- **Replacement** — a replica whose lease expired (SIGKILLed executor)
  or whose engine died is repaired, not scaled around: same identity,
  fresh fencing epoch minted BEFORE the replacement's first beat
  (PR 12 — a partitioned corpse can never serve stale), on whatever
  free executor exists. Replacement is exempt from scale cooldowns —
  it restores the target, it doesn't change it.
- **Evidence-gated cold start** — a fleet that has served NOTHING
  (zero completions, empty queues, idle slots) holds: the controller
  never scales on the absence of evidence.

Every decision is recorded supervisor-style — a ``tracing.EventLog``
entry carrying the evidence snapshot (the per-replica views the
decision priced) — and mirrored as a FlightRecorder instant into the
ROUTER's span ring, so ``GET /debug/trace`` timelines show scale
events against the very request spans that triggered them. Counters
(``tfos_autoscale_*``) register into the router's metrics registry and
render on its ``/metrics``.

The decision function (:func:`decide`) is pure — views in, decision
out, time injected — so tests/test_autoscale.py pins the policy table
without sockets, exactly as fleet.route_order and ReplicaHealth are
pinned.
"""

import logging
import threading
import time

from tensorflowonspark_tpu import tracing

logger = logging.getLogger(__name__)


class AutoscalePolicy(object):
    """Scaling rules: SLO thresholds, watermarks, hysteresis, bounds.

    Args:
      min_replicas / max_replicas: the fleet's width clamps.
      queue_wait_slo_s: a live replica's ``queue_wait_ewma_s`` past
        this is an SLO breach (work is waiting for slots).
      ttft_p99_slo_s: optional TTFT p99 target (read from each
        replica's beat-carried histogram snapshot); None disables.
      occupancy_high: mean slot-occupancy fraction at or above which a
        STANDING queue (any ``queue_depth`` > 0) reads as saturation —
        occupancy alone is healthy utilization, occupancy + queue is a
        breach.
      occupancy_low: mean occupancy at or below which (with empty
        queues everywhere) the fleet reads as idle — the scale-down
        signal.
      up_cooldown_s: minimum seconds between scale-UPs (fast — a
        breach under load deserves quick capacity, but never a tight
        spawn loop).
      down_cooldown_s: minimum seconds since the LAST SCALE IN EITHER
        DIRECTION before a scale-down (slow — the hysteresis that
        stops a bursty workload flapping the fleet).
      dead_after_s: lease age past which a replica is presumed lost
        (executor death) and REPLACED.
    """

    def __init__(self, min_replicas=1, max_replicas=4,
                 queue_wait_slo_s=0.75, ttft_p99_slo_s=None,
                 occupancy_high=0.85, occupancy_low=0.25,
                 up_cooldown_s=2.0, down_cooldown_s=20.0,
                 dead_after_s=3.0, burn_rate_up_threshold=None):
        if int(min_replicas) < 1:
            raise ValueError("min_replicas must be >= 1")
        if int(max_replicas) < int(min_replicas):
            raise ValueError("max_replicas must be >= min_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_wait_slo_s = float(queue_wait_slo_s)
        self.ttft_p99_slo_s = None if ttft_p99_slo_s is None \
            else float(ttft_p99_slo_s)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.dead_after_s = float(dead_after_s)
        #: SLO-plane coupling (PR 20): when the router's SloMonitor
        #: reports a fast-window error-budget burn above this multiple,
        #: that is UP pressure even before queues visibly back up — a
        #: gray replica burns budget while the healthy one keeps the
        #: queue short. None disables the term.
        self.burn_rate_up_threshold = None if burn_rate_up_threshold \
            is None else float(burn_rate_up_threshold)


class ScaleDecision(object):
    """One evaluated decision: ``action`` (hold/up/down/replace),
    the human reason, the replica it targets (down/replace), the
    evidence views it priced, and — for tiered fleets (PR 17) — the
    tier the decision sizes (None on homogeneous fleets; a spawn
    applied from a tiered decision carries it to
    ``ServingFleet.spawn_replica(tier=...)``)."""

    HOLD, UP, DOWN, REPLACE = "hold", "up", "down", "replace"

    def __init__(self, action, reason, replica_id=None, evidence=None,
                 tier=None):
        self.action = action
        self.reason = reason
        self.replica_id = replica_id
        self.evidence = evidence or {}
        self.tier = tier

    def __repr__(self):
        return "ScaleDecision({}, {!r}, replica={}{})".format(
            self.action, self.reason, self.replica_id,
            ", tier={}".format(self.tier) if self.tier else "")


def replica_view(rid, info):
    """One replica's compact decision view from its serving-snapshot
    entry (None info = tracked by the fleet but no lease at all)."""
    info = info or {}
    gauges = info.get("serving") or {}
    metrics = info.get("metrics") or {}
    counts = ((metrics.get("counters") or {}).get("tfos_serving")
              or {}).get("counts") or {}
    ttft = (metrics.get("hists") or {}).get("tfos_serving_ttft_seconds")
    slots = int(gauges.get("slots") or 0)
    return {
        "replica_id": str(rid),
        "age": info.get("age"),
        "alive": gauges.get("alive", False),
        "draining": bool(gauges.get("draining")),
        "queue_depth": int(gauges.get("queue_depth") or 0),
        # per-priority queue split (PR 18): lets decide() tell a HIGH-
        # class breach (buy hardware) from LOW-only backlog (declared
        # soak load — tolerate). Empty on engines predating the gauge.
        "queue_by_class": dict(gauges.get("queue_by_class") or {}),
        "slot_occupancy": int(gauges.get("slot_occupancy") or 0),
        "slots": slots,
        "queue_wait_ewma_s": float(gauges.get("queue_wait_ewma_s")
                                   or 0.0),
        "kv_blocks_free": gauges.get("kv_blocks_free"),
        "kv_blocks_total": gauges.get("kv_blocks_total"),
        "completed": int(counts.get("requests_completed") or 0),
        "ttft_p99_s": tracing.snapshot_quantile(ttft, 0.99)
        if ttft else None,
        # prefix warmth (PR 16): the beat-carried chain digest,
        # summarized as summed resident depth — the signal that makes
        # sustained-idle retirement prefer the COLDEST replica, so a
        # scale-down doesn't destroy the fleet's hottest cache
        "prefix_warmth": _digest_warmth(gauges.get("prefix_digest")),
        "generated_prefix_hit_blocks": int(
            gauges.get("generated_prefix_hit_blocks") or 0),
        "executor": (info.get("host") or {}).get("executor"),
        # disaggregation tier (PR 17): partitions decide() into
        # independent per-tier sizing pools ("mixed" — every pre-tier
        # replica — keeps the fleet one pool)
        "tier": str(gauges.get("tier") or "mixed"),
    }


def _digest_warmth(digest):
    """Scalar warmth of one beat-carried prefix digest: summed chain
    depths (blocks of resident, reusable prefix). Zero for contiguous
    replicas' zero schema or malformed entries — cold by definition."""
    warmth = 0
    for entry in digest or []:
        try:
            warmth += max(0, int(entry[1]))
        except (TypeError, ValueError, IndexError):
            continue
    return warmth


def _load_key(view):
    """Least-loaded ordering for scale-down victim selection (the
    retiree should strand as little in-flight work as possible)."""
    return (view["queue_depth"] + view["slot_occupancy"],
            view["queue_wait_ewma_s"], view["replica_id"])


def _retire_key(view):
    """Scale-down victim ordering (PR 16): coldest cache first —
    summed digest depth, then the generated-prefix hit tally (a
    replica actively serving multi-turn reuse is the last thing to
    retire) — with :func:`_load_key` breaking warmth ties, so among
    equally cold replicas the retiree still strands the least
    in-flight work. ``view.get`` defaults keep the key total for
    hand-built test views."""
    return (int(view.get("prefix_warmth") or 0),
            int(view.get("generated_prefix_hit_blocks") or 0)) \
        + _load_key(view)


def _state_key(base, tier):
    """Cooldown-stamp key: per-tier sub-state (``last_up:prefill``)
    for tiered pools, the legacy flat key for homogeneous fleets —
    each tier's hysteresis runs independently (a prefill burst must
    not block a decode scale-down, and vice versa)."""
    return base if tier is None else "{}:{}".format(base, tier)


def decide(policy, views, state, now, burn_rate=None):
    """PURE scaling decision: per-replica ``views`` (see
    :func:`replica_view`), controller ``state`` ({"last_up",
    "last_down"} monotonic stamps or None, plus per-tier
    ``last_up:<tier>`` sub-keys on tiered fleets), injected ``now``
    -> :class:`ScaleDecision`. Never mutates ``state`` — the
    controller stamps it only when an action actually applies.

    Rule order: replacement (repair) outranks scaling; breaches
    outrank idleness; every scale respects the clamps, its cooldown,
    and the no-evidence gate. Tiered fleets (PR 17) are sized PER
    TIER: each tier is its own pool with its own cooldown sub-state
    and its own min/max clamp (the policy's bounds apply to each tier
    independently — a saturated prefill tier scales on its backlog
    while an idle decode tier shrinks on its slots, in the same
    poll cycle's priority order: any UP beats any DOWN)."""
    # -- repair: a dead member is replaced, cooldowns notwithstanding
    # (tier-blind — a corpse is repaired whatever it served; the
    # fleet's spawn path re-derives its tier from the identity)
    for view in views:
        if view["draining"]:
            continue
        lease_dead = view["age"] is None \
            or view["age"] > policy.dead_after_s
        if lease_dead or not view["alive"]:
            return ScaleDecision(
                ScaleDecision.REPLACE,
                "lease expired (age {})".format(view["age"])
                if lease_dead else "engine dead under a live lease",
                replica_id=view["replica_id"],
                evidence={"views": views},
                tier=view.get("tier"))
    tiers = sorted({str(v.get("tier") or "mixed") for v in views})
    if len(tiers) <= 1:
        return _decide_pool(policy, views, state, now,
                            burn_rate=burn_rate)
    decisions = [
        _decide_pool(policy,
                     [v for v in views
                      if str(v.get("tier") or "mixed") == tier],
                     state, now, tier=tier, burn_rate=burn_rate)
        for tier in tiers]
    for decision in decisions:
        if decision.action == ScaleDecision.UP:
            return decision
    for decision in decisions:
        if decision.action != ScaleDecision.HOLD:
            return decision
    return ScaleDecision(
        ScaleDecision.HOLD,
        "; ".join("{}: {}".format(d.tier, d.reason)
                  for d in decisions),
        evidence={"tiers": {d.tier: d.evidence for d in decisions}})


def _decide_pool(policy, views, state, now, tier=None, burn_rate=None):
    """One pool's scaling verdict (the whole fleet, or one tier of a
    tiered fleet): the breach/idle policy table over ``views``, with
    cooldown stamps read from the pool's own sub-state."""
    live = [v for v in views
            if v["age"] is not None and v["age"] <= policy.dead_after_s
            and v["alive"] and not v["draining"]]
    evidence = {"views": views, "live": len(live)}
    if tier is not None:
        evidence["tier"] = tier
    if not live:
        return ScaleDecision(ScaleDecision.HOLD, "no live replicas",
                             evidence=evidence, tier=tier)
    total_slots = sum(v["slots"] for v in live) or 1
    occupancy = sum(v["slot_occupancy"] for v in live) / float(total_slots)
    queue = sum(v["queue_depth"] for v in live)
    max_qwait = max(v["queue_wait_ewma_s"] for v in live)
    ttfts = [v["ttft_p99_s"] for v in live if v["ttft_p99_s"] is not None]
    completed = sum(v["completed"] for v in live)
    by_class = {"high": 0, "normal": 0, "low": 0}
    for v in live:
        for cls, n in (v.get("queue_by_class") or {}).items():
            if cls in by_class:
                try:
                    by_class[cls] += int(n)
                except (TypeError, ValueError):
                    continue
    evidence.update(occupancy=round(occupancy, 3), queue_depth=queue,
                    max_queue_wait_ewma_s=round(max_qwait, 4),
                    ttft_p99_s=round(max(ttfts), 4) if ttfts else None,
                    completed=completed,
                    queue_by_class=dict(by_class))
    # -- evidence-gated cold start: a fleet that has served nothing
    # and holds no work must not scale on the absence of evidence
    if completed == 0 and queue == 0 and occupancy == 0.0:
        return ScaleDecision(ScaleDecision.HOLD, "cold (no evidence)",
                             evidence=evidence, tier=tier)
    # breach terms are gated on STANDING work (queue > 0): the
    # queue-wait EWMA and TTFT histogram are history — they hold their
    # last burst's values while the fleet sits idle, and a breach that
    # no current request is experiencing must not pin the fleet wide
    # (it would also block every scale-down forever)
    breach = []
    if queue > 0 and max_qwait > policy.queue_wait_slo_s:
        breach.append("queue_wait_ewma {:.3f}s > SLO {:.3f}s".format(
            max_qwait, policy.queue_wait_slo_s))
    if policy.ttft_p99_slo_s is not None and ttfts and queue > 0 \
            and max(ttfts) > policy.ttft_p99_slo_s:
        breach.append("ttft_p99 {:.3f}s > SLO {:.3f}s".format(
            max(ttfts), policy.ttft_p99_slo_s))
    if occupancy >= policy.occupancy_high and queue > 0:
        breach.append(
            "slots saturated ({:.0%}) with {} queued".format(
                occupancy, queue))
    # SLO-plane burn (PR 20): evidence-gated on the pool having served
    # at all — unlike the queue-gated terms above, budget burn IS
    # current pain (the windowed SLI only moves while bad requests
    # land), so a gray replica scales the pool before queues back up
    if burn_rate is not None \
            and policy.burn_rate_up_threshold is not None \
            and completed > 0 \
            and burn_rate > policy.burn_rate_up_threshold:
        evidence["burn_rate"] = round(burn_rate, 3)
        breach.append(
            "error-budget burn {:.1f}x > {:.1f}x threshold".format(
                burn_rate, policy.burn_rate_up_threshold))
    if breach:
        reason = "; ".join(breach)
        # per-priority breach view (PR 18): a backlog made ENTIRELY of
        # LOW-class work is declared soak load — it opted into waiting
        # (absorbing idle capacity is its whole job), so it tolerates
        # the breach instead of buying hardware; any HIGH/normal work
        # standing in the queue scales as before. Guarded on the class
        # tally accounting for the WHOLE queue: replicas predating the
        # gauge report nothing, and an unaccounted backlog must keep
        # the legacy scale-up behavior.
        if queue > 0 and by_class["high"] + by_class["normal"] == 0 \
                and by_class["low"] >= queue:
            return ScaleDecision(
                ScaleDecision.HOLD,
                "LOW-class-only backlog tolerated: " + reason,
                evidence=evidence, tier=tier)
        if len(live) >= policy.max_replicas:
            return ScaleDecision(
                ScaleDecision.HOLD,
                "SLO breach but at max_replicas ({}): {}".format(
                    policy.max_replicas, reason), evidence=evidence,
                tier=tier)
        last_up = state.get(_state_key("last_up", tier))
        if last_up is not None and now - last_up < policy.up_cooldown_s:
            return ScaleDecision(
                ScaleDecision.HOLD,
                "SLO breach inside up-cooldown ({:.1f}s < {:.1f}s)"
                .format(now - last_up, policy.up_cooldown_s),
                evidence=evidence, tier=tier)
        return ScaleDecision(ScaleDecision.UP, reason,
                             evidence=evidence, tier=tier)
    if queue == 0 and occupancy <= policy.occupancy_low:
        if len(live) <= policy.min_replicas:
            return ScaleDecision(
                ScaleDecision.HOLD, "idle at min_replicas",
                evidence=evidence, tier=tier)
        if completed == 0:
            # live gauges can read idle while every request so far
            # shed/failed — never shrink a fleet that has not proven
            # it can serve
            return ScaleDecision(
                ScaleDecision.HOLD, "idle but zero completions",
                evidence=evidence, tier=tier)
        stamps = [t for t in (state.get(_state_key("last_up", tier)),
                              state.get(_state_key("last_down", tier)))
                  if t is not None]
        last_scale = max(stamps) if stamps else None
        if last_scale is not None \
                and now - last_scale < policy.down_cooldown_s:
            return ScaleDecision(
                ScaleDecision.HOLD,
                "idle inside down-cooldown ({:.1f}s < {:.1f}s)".format(
                    now - last_scale, policy.down_cooldown_s),
                evidence=evidence, tier=tier)
        victim = min(live, key=_retire_key)
        return ScaleDecision(
            ScaleDecision.DOWN,
            "idle (occupancy {:.0%} <= {:.0%}, empty queues; "
            "retiring coldest cache)".format(
                occupancy, policy.occupancy_low),
            replica_id=victim["replica_id"], evidence=evidence,
            tier=tier)
    return ScaleDecision(ScaleDecision.HOLD, "within SLO",
                         evidence=evidence, tier=tier)


class AutoscaleController(object):
    """Driver-side control loop binding :func:`decide` to a
    ``fleet.ServingFleet``: read the serving BEAT snapshot, decide,
    apply (spawn / retire / replace), record. Runs on its own daemon
    thread (:meth:`start`); :meth:`poll_once` is exposed so tests
    drive it deterministically."""

    def __init__(self, fleet, policy=None, interval=0.25,
                 drain_timeout=None, events=None, spawn_timeout=None):
        self.fleet = fleet
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.interval = float(interval)
        #: bound on a retirement's zero-loss drain (None = wait for
        #: the admitted work, the zero-loss posture)
        self.drain_timeout = drain_timeout
        self.spawn_timeout = spawn_timeout
        #: supervisor-style decision log, evidence snapshot per entry
        self.events = events if events is not None else tracing.EventLog()
        self.counters = tracing.Counters()
        self._state = {"last_up": None, "last_down": None}
        self._last_record = None
        self._last_note = None
        # one control step at a time: poll_once is public (tests and
        # operators drive it) AND the loop thread calls it — two
        # concurrent evaluations of the same evidence would BOTH
        # apply (a double scale-down retires two replicas for one
        # idle verdict) and race the cooldown stamps and the
        # decision-suppression memos (unlocked read-modify-writes).
        # Pinned by test_autoscale.py's two-thread barrier test.
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        router = getattr(fleet, "router", None)
        #: scale instants land in the ROUTER's flight ring so
        #: /debug/trace shows them against request spans
        self.flight = router.flight if router is not None \
            else tracing.flight_recorder()
        if router is not None:
            router.metrics.add_counters("tfos_autoscale", self.counters)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="tfos-autoscale", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("autoscale poll failed")
            self._stop.wait(self.interval)

    # -- one control step --------------------------------------------------

    def views(self):
        """Per-replica decision views for every replica the fleet
        TRACKS (a tracked replica with no lease at all shows age None
        — the replacement signal)."""
        snapshot = self.fleet.reservation.serving_snapshot()
        return [replica_view(r.replica_id,
                             snapshot.get(r.replica_id))
                for r in list(self.fleet.replicas)]

    def poll_once(self, now=None):
        """One full control step (read -> decide -> record -> apply),
        serialized: a caller landing while another step is mid-apply
        waits and then evaluates FRESH state (the first step's stamps
        and fleet changes), so one idle verdict can never retire two
        replicas."""
        with self._lock:
            # `now` defaults AFTER the lock: a step that waited out a
            # long apply must price cooldowns at the time it actually
            # runs, not at the time it queued
            return self._poll_locked(
                now if now is not None else time.monotonic())

    def _poll_locked(self, now):
        recovering = getattr(self.fleet.reservation, "recovering",
                             None)  # stub reservations lack it
        if recovering is not None and recovering():
            # control-plane recovery grace (PR 19): a restarted
            # reservation server's snapshot is floors-without-leases
            # until the incumbents re-announce — every view reads
            # age None, the REPLACE signature. Scaling on that would
            # spawn replacements (fresh epochs!) for replicas that
            # are alive and about to re-register; hold until the
            # grace window clears.
            self.counters.inc("decisions")
            decision = ScaleDecision(
                ScaleDecision.HOLD, "reservation server recovering "
                "(journal floors seeded, awaiting re-announce)")
            self._record(decision, 0, len(self.fleet.replicas))
            return decision
        views = self.views()
        burn_rate = None
        if self.policy.burn_rate_up_threshold is not None:
            # SLO-plane coupling (PR 20): the router's monitor samples
            # on demand; the largest fast-window burn across specs is
            # the scalar UP-pressure signal. Best-effort — a fleet
            # without a router (or a sampling hiccup) scales on the
            # classic terms alone.
            monitor = getattr(getattr(self.fleet, "router", None),
                              "slo", None)
            if monitor is not None:
                try:
                    burn_rate = monitor.max_fast_burn()
                except Exception:  # noqa: BLE001 - advisory signal
                    burn_rate = None
        decision = decide(self.policy, views, self._state, now,
                          burn_rate=burn_rate)
        self.counters.inc("decisions")
        live = sum(1 for v in views
                   if v["age"] is not None
                   and v["age"] <= self.policy.dead_after_s
                   and v["alive"] and not v["draining"])
        target = len(self.fleet.replicas)
        if decision.action == ScaleDecision.UP:
            target += 1
        elif decision.action == ScaleDecision.DOWN:
            target -= 1
        self.counters.gauge("replicas_live", live)
        self.counters.gauge("replicas_target", target)
        self._record(decision, live, target)
        if decision.action == ScaleDecision.UP:
            self._apply_up(decision, now)
        elif decision.action == ScaleDecision.DOWN:
            self._apply_down(decision, now)
        elif decision.action == ScaleDecision.REPLACE:
            self._apply_replace(decision, now)
        return decision

    def _record(self, decision, live, target):
        """Supervisor-style decision trail: every DISTINCT decision is
        logged with its evidence snapshot (and non-holds mirrored as
        router-ring trace instants). Consecutive identical decisions —
        a steady hold, but equally a REPLACE re-issued every poll
        while no capacity exists — are logged once: the trail shows
        state changes, not a poll-rate heartbeat that would churn the
        EventLog ring out of its real history."""
        key = (decision.action, decision.reason, decision.replica_id,
               decision.tier)
        if key == self._last_record:
            return
        self._last_record = key
        self.events.record(
            "autoscale_decision", action=decision.action,
            reason=decision.reason, replica=decision.replica_id,
            tier=decision.tier, replicas_live=live,
            replicas_target=target, evidence=decision.evidence)
        if decision.action != ScaleDecision.HOLD:
            self.flight.instant(
                "autoscale_" + decision.action,
                reason=decision.reason,
                replica=decision.replica_id or "",
                replicas_live=live, replicas_target=target)
            logger.warning("autoscale %s: %s (live %d -> target %d)",
                           decision.action, decision.reason, live,
                           target)

    def _note_once(self, name, **detail):
        """Record an apply-side event unless it is an identical repeat
        of the previous one — a blocked replacement re-evaluated every
        poll must not flood the EventLog (counters still tick)."""
        key = (name, tuple(sorted(detail.items())))
        if key == self._last_note:
            return
        self._last_note = key
        self.events.record(name, **detail)

    def _applied(self, name, **detail):
        """Record a SUCCESSFUL apply (always logged; resets the
        repeat-suppression state so a later identical failure is a
        fresh story)."""
        self._last_note = None
        self._last_record = None
        self.events.record(name, **detail)

    def _apply_up(self, decision, now):
        from tensorflowonspark_tpu import fleet as fleet_mod

        up_key = _state_key("last_up", decision.tier)
        if self.fleet.placement == "executors" \
                and self.fleet.free_executor() is None:
            # the regrow-probe gate: capacity must EXIST; a blocked
            # scale-up is a recorded fact, not a spin
            self.counters.inc("scale_up_blocked")
            self._note_once("autoscale_blocked",
                            reason="no free executor")
            self._state[up_key] = now  # re-probe after the cooldown
            return
        try:
            # a tiered decision's spawn lands IN that tier (PR 17):
            # sizing the prefill pool must grow a prefill replica
            replica = self.fleet.spawn_replica(
                timeout=self.spawn_timeout, tier=decision.tier)
        except fleet_mod.NoCapacity as e:
            self.counters.inc("scale_up_blocked")
            self._note_once("autoscale_blocked", reason=str(e))
            self._state[up_key] = now
            return
        self._state[up_key] = now
        self.counters.inc("scale_ups")
        self._applied("autoscale_scaled_up",
                      replica=replica.replica_id,
                      tier=decision.tier,
                      executor=getattr(replica, "executor_id", None))

    def _apply_down(self, decision, now):
        clean = self.fleet.retire_replica(
            decision.replica_id, drain_timeout=self.drain_timeout)
        self._state[_state_key("last_down", decision.tier)] = now
        self.counters.inc("scale_downs")
        if not clean:
            self.counters.inc("unclean_retirements")
        self._applied("autoscale_scaled_down",
                      replica=decision.replica_id,
                      tier=decision.tier,
                      drained_clean=bool(clean))

    def _supervisor_watches(self, replica):
        """True when the fleet's supervisor holds a RestartEngine
        watch over THIS replica object — only then is in-process
        engine death someone else's repair. A replica spawned after
        supervise() (or an unsupervised fleet) has no watcher, and
        deferring for it would wedge the controller forever."""
        sup = getattr(self.fleet, "supervisor", None)
        if sup is None:
            return False
        return any(entry.get("replica") is replica
                   for entry in getattr(sup, "_watched", []))

    def _apply_replace(self, decision, now):
        from tensorflowonspark_tpu import fleet as fleet_mod

        rid = decision.replica_id
        replica = self.fleet._replica(rid)
        if replica is None:
            return
        info = self.fleet.reservation.serving_snapshot().get(rid) or {}
        lease_fresh = (info.get("age") or 1e9) <= self.policy.dead_after_s
        remote = getattr(replica, "remote", False)
        try:
            if lease_fresh and not remote:
                if self._supervisor_watches(replica):
                    # the supervisor's RestartEngine owns this repair;
                    # replacing from here would race it
                    self._note_once(
                        "autoscale_replace_deferred", replica=rid,
                        reason="in-process engine death -> supervisor")
                    return
                # UNWATCHED in-process engine death: repair here —
                # stop the corpse, respawn in place, readmit
                old = replica.server.engine
                if old is not None:
                    old.stop()
                replica.respawn_engine()
                if self.fleet.router is not None:
                    self.fleet.router.readmit(rid, owner=None)
            elif lease_fresh:
                # executor alive, engine dead: respawn IN PLACE over
                # the lifecycle RPC — cheaper than a cross-executor
                # replacement and keeps the placement ledger intact
                replica.respawn_engine()
                if self.fleet.router is not None:
                    self.fleet.router.readmit(rid, owner=None)
            elif not remote:
                # driver-placement dead lease: the replica OBJECT
                # lives in this process, so the lease died because
                # its beat loop stopped (fenced by an operator mint,
                # or a wedged beat) — not because an executor
                # vanished. replace_replica cannot apply (it raises
                # for driver fleets, which used to wedge the
                # controller in a permanent REPLACE loop); the repair
                # verb is re_register: fresh epoch, restarted beat
                # loop, same engine
                replica.re_register()
                if self.fleet.router is not None:
                    self.fleet.router.readmit(rid, owner=None)
            else:
                self.fleet.replace_replica(rid,
                                           timeout=self.spawn_timeout)
        except fleet_mod.NoCapacity as e:
            self.counters.inc("scale_up_blocked")
            self._note_once("autoscale_blocked", replica=rid,
                            reason=str(e))
            return
        except Exception as e:  # noqa: BLE001 - retried next poll
            logger.warning("autoscale replacement of %s failed: %s",
                           rid, e)
            self._note_once("autoscale_replace_failed", replica=rid,
                            reason=str(e))
            return
        self.counters.inc("replacements")
        self._applied("autoscale_replaced", replica=rid,
                      in_place=lease_fresh)
