"""Feed-queue sentinel markers.

Reference: ``tensorflowonspark/marker.py`` (SURVEY.md §2 "Feed markers") —
sentinels pushed through the input queue so the consumer (:class:`DataFeed`)
can detect partition/epoch boundaries and end-of-feed without a side channel.

TPU-native difference: queue items are *record batches* (lists), not single
records (the reference's per-record pickle through a manager proxy is its
known feed bottleneck — SURVEY.md §7.3). Markers still travel the queue as
bare objects between batches.
"""


class Marker(object):
    """Base class for all feed-queue sentinels."""

    def __repr__(self):  # pragma: no cover - debug aid
        return "<{}>".format(type(self).__name__)


class EndPartition(Marker):
    """End of one input partition (reference: ``marker.EndPartition``).

    ``DataFeed.next_batch`` returns a short batch when it sees one, so batch
    boundaries never straddle partitions/epochs.
    """


class EndFeed(Marker):
    """End of the entire feed: no more data will ever arrive.

    Pushed by ``shutdown()`` so background consumers unblock deterministically
    (the reference signals this with ``None`` items; an explicit type is
    self-documenting and survives queues that carry legitimate ``None``\\ s).
    """
