"""Training checkpoint/resume on orbax, with chief-only commit.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): the reference
delegates checkpointing to TF (MonitoredTrainingSession / Keras callbacks
writing to shared storage); recovery = resubmit + restore latest. The
TPU-native analog is orbax-checkpoint with the same division of labor:
the framework supplies a manager wired to the node's role (only the chief
commits under pure DP, where state is replicated), user code decides when
to save.
"""

import logging
import os

logger = logging.getLogger(__name__)


class Checkpointer(object):
    """Step-indexed train-state checkpoints under ``directory``.

    Args:
      directory: checkpoint root (shared storage in multi-host setups).
      chief: whether this process commits (``ctx.job_name`` in the master
        family). Non-chief saves are no-ops, mirroring chief-only export.
      max_to_keep: retention.
    """

    def __init__(self, directory, chief=True, max_to_keep=3):
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import fs

        self.directory = os.path.abspath(
            fs.require_local(directory, "checkpointing"))
        self.chief = chief
        if chief:
            os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=chief))

    def save(self, step, state, force=False):
        """Commit ``state`` at ``step`` (chief only); returns True if saved."""
        if not self.chief:
            return False
        import jax
        import orbax.checkpoint as ocp

        state = jax.tree.map(lambda x: x, state)  # shallow copy
        saved = self._mgr.save(int(step), args=ocp.args.StandardSave(state),
                               force=force)
        return bool(saved)

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, state_like, step=None):
        """Restore into the structure of ``state_like`` (init-shaped state).

        Returns the restored state, or None if no checkpoint exists.
        """
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        return self._mgr.restore(int(step),
                                 args=ocp.args.StandardRestore(state_like))

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def hook(checkpointer, every_steps=100):
    """Trainer ``train_loop`` hook: save every N steps."""

    def _hook(step_no, state, metrics):
        if step_no % every_steps == 0:
            checkpointer.save(int(state["step"]), state)

    return _hook
