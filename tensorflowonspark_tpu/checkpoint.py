"""Training checkpoint/resume on orbax: replicated AND sharded states.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): the reference
delegates checkpointing to TF (MonitoredTrainingSession / Keras callbacks
writing to shared storage); recovery = resubmit + restore latest. The
TPU-native analog is orbax-checkpoint with the same division of labor —
the framework wires the manager to the node's role, user code decides
when to save — but the commit protocol depends on how the state is laid
out, which the reference (pure DP only) never had to distinguish:

- **Replicated state** (pure DP): every process holds identical bytes, so
  only the chief commits and non-chief ``save()`` is a cheap no-op.
- **Sharded state** (TP/PP/EP, or DP with a process-spanning global batch
  axis): each process holds only its own shards. ALL processes must
  participate in the orbax save (orbax gathers/coordinates internally via
  ``jax.distributed``); a chief-only save would silently drop every
  non-addressable shard and restore garbage. ``save()`` detects the
  layout per call and picks the protocol — and *raises* on the one
  combination that cannot be correct (a non-participating ``chief=False``
  process holding non-replicated state with no distributed runtime to
  coordinate through).

Remote roots: orbax brings its own storage drivers (tensorstore), so a
``gs://``-style root is passed through verbatim when
``allow_remote=True``; the default is a loud local-path check
(fs.require_local) because this image bundles no remote-FS client and a
URL silently abspath'd into ``./gs:`` is the failure mode being blocked.
"""

import logging
import os

logger = logging.getLogger(__name__)


def is_fully_replicated(state):
    """True when every device array in ``state`` is fully replicated.

    Host numpy arrays / scalars count as replicated (every process can
    reconstruct them); a single non-replicated jax.Array makes the whole
    state sharded for checkpoint-protocol purposes.
    """
    import jax

    for leaf in jax.tree.leaves(state):
        if isinstance(leaf, jax.Array):
            try:
                if not leaf.sharding.is_fully_replicated:
                    return False
            except AttributeError:  # non-standard array-likes: assume ok
                pass
    return True


def respec_like(state, mesh):
    """Cross-mesh restore template: ``state``'s shapes/dtypes with every
    NamedSharding re-bound onto ``mesh``.

    The elastic-resize enabler (docs/fault_tolerance.md "Elastic
    resize"): GSPMD shardings are declarative — a ``PartitionSpec``
    names mesh AXES, not devices — so the same state lays out on any
    mesh whose named axes still factor its shapes. This maps each
    device-array leaf (``jax.Array`` or ``jax.ShapeDtypeStruct``
    carrying a ``NamedSharding``) to a ``ShapeDtypeStruct`` with the
    same spec over ``mesh``; host arrays/scalars pass through
    unchanged. Feed the result to :meth:`Checkpointer.restore` and
    orbax reshards the checkpoint onto the new mesh — a save taken at
    one width restores bitwise at another.

    Raises ``ValueError`` naming the leaf and axis when a spec names an
    axis ``mesh`` does not have (the one way a resized mesh can fail to
    carry the old layout — ``respec_for_width`` keeps non-data axes
    intact precisely so this never fires on a data-axis resize).
    """
    import jax
    from jax.sharding import NamedSharding

    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    axes = set(mesh.axis_names)
    out = []
    for path, leaf in leaves:
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(leaf, (jax.Array, jax.ShapeDtypeStruct)) \
                or not isinstance(sharding, NamedSharding):
            out.append(leaf)
            continue
        spec = sharding.spec
        named = set()
        for entry in spec:
            if entry is None:
                continue
            named |= set(entry if isinstance(entry, tuple) else (entry,))
        missing = named - axes
        if missing:
            raise ValueError(
                "cannot respec leaf {} onto mesh axes {}: its "
                "PartitionSpec {} names axis(es) {} the target mesh "
                "does not have".format(
                    jax.tree_util.keystr(path), sorted(axes), spec,
                    sorted(missing)))
        out.append(jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, out)


class Checkpointer(object):
    """Step-indexed train-state checkpoints under ``directory``.

    Args:
      directory: checkpoint root. Must be shared storage (NFS or a remote
        scheme with ``allow_remote=True``) in multi-host setups.
      chief: whether this node is in the master family (``ctx.job_name``).
        Governs *replicated* saves only; sharded saves are all-process by
        construction.
      max_to_keep: retention.
      allow_remote: pass scheme'd roots (``gs://...``) straight to orbax/
        tensorstore instead of rejecting them. The caller owns making sure
        the scheme is one orbax's storage layer can actually serve.
    """

    def __init__(self, directory, chief=True, max_to_keep=3,
                 allow_remote=False):
        import jax
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import fs

        if allow_remote and fs.scheme_of(directory) is not None:
            self.directory = os.fspath(directory)
            self._remote = True
        else:
            self.directory = os.path.abspath(
                fs.require_local(directory, "checkpointing"))
            self._remote = False
        self.chief = chief
        if not self._remote:
            # Every process needs the LOCAL root to exist before the
            # manager is built: current orbax walks the root at
            # construction (`_load_checkpoint_infos`) and raises on a
            # missing path, so a non-chief with `create=False` could
            # never construct against a not-yet-created directory. An
            # empty root is inert (no steps), and exist_ok makes the
            # multi-process mkdir race benign — commit semantics still
            # belong to orbax's create/primary-host logic below.
            os.makedirs(self.directory, exist_ok=True)
        # ``create`` must be PROCESS-UNIFORM under jax.distributed:
        # orbax's create path runs a named sync_global_devices barrier,
        # so chief-only create (create=chief) sends the chief into a
        # collective the workers never enter — the next collective then
        # dies inside gloo with a payload-size mismatch (found by the
        # multi-process sharded recovery test). Multi-process: everyone
        # passes create=True and orbax's primary-host logic does the one
        # mkdir. Single-process keeps the chief-only behavior.
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=chief or jax.process_count() > 1))
        # Skip-decision bookkeeping (ADVICE r5): the already-persisted
        # guard in save() must be PROVABLY CONSISTENT across processes —
        # under jax.distributed, orbax's save is a collective, so if one
        # process skips while a sibling enters, the sibling hangs at the
        # barrier forever. A live all_steps() scan per call is not
        # consistent: a racing async commit can make processes disagree
        # mid-run. So the decision derives only from (a) this snapshot,
        # taken once before this run issues any saves (every process
        # sees the same settled disk state at construction), and (b) the
        # steps THIS instance saved — both identical across processes
        # that make the same save() calls, which the collective contract
        # already requires. Boundary of the guarantee: the snapshot
        # assumes disk is SETTLED at construction, i.e. no other
        # incarnation's async commit is landing while processes
        # construct. The framework's restart story satisfies this (a
        # resubmitted job's previous savers are dead before the
        # reservation barrier forms and trainers build checkpointers);
        # an external writer racing construction is outside the
        # contract and surfaces as StepAlreadyExistsError, not a hang.
        self._steps_on_disk = frozenset(
            int(s) for s in self._mgr.all_steps())
        self._saved_steps = set()

    def save(self, step, state, force=False):
        """Commit ``state`` at ``step``; returns True if this process saved.

        An already-persisted step is never overwritten: the call
        returns False (``force`` governs orbax's save-interval policy,
        not step replacement — orbax itself raises on an existing step
        even with force). "Already persisted" means on disk when this
        Checkpointer was constructed, or saved through this instance —
        a deliberately process-consistent definition (see __init__); a
        step landed mid-run by an unrelated writer surfaces as orbax's
        StepAlreadyExistsError instead of a silent skip. To genuinely
        replace a step, delete it first.

        Replicated state: chief commits, everyone else no-ops. Sharded
        state: every process participates (orbax coordinates the
        multi-process gather); a ``chief=False`` process that holds
        non-replicated state *without* a distributed runtime raises —
        its shards could never reach storage and the checkpoint would
        restore garbage with no warning.
        """
        import jax
        import orbax.checkpoint as ocp

        replicated = is_fully_replicated(state)
        if not self.chief and replicated and jax.process_count() == 1:
            # The chief's bytes are ours too. Only safe to skip OUTSIDE a
            # distributed runtime: orbax's save is a collective with
            # global barriers under jax.distributed, so a non-chief that
            # returned early there would strand the chief at the barrier.
            # (Multi-process non-chief saves are write-free: orbax's
            # primary-host logic commits once.)
            return False
        if not self.chief and not replicated and jax.process_count() == 1:
            raise ValueError(
                "Checkpointer(chief=False).save() got a non-replicated "
                "(sharded) state in a single-process runtime: this "
                "process's shards cannot reach the checkpoint and a "
                "restore would return garbage. Sharded states need either "
                "all processes saving under jax.distributed, or "
                "chief=True in the single-process case.")
        step = int(step)
        if step in self._saved_steps or step in self._steps_on_disk:
            # Already persisted (e.g. a periodic hook fired on the final
            # step and the epilogue force-saves the same step): a no-op,
            # not orbax's StepAlreadyExistsError — the caller's intent
            # ("step N must be on disk") is satisfied either way. The
            # decision uses only locally tracked saves + the init-time
            # disk snapshot (never a live all_steps() scan), so every
            # process in a collective save skips or enters IDENTICALLY —
            # a racing async commit can no longer strand some processes
            # at orbax's barrier while others return False.
            return False
        state = jax.tree.map(lambda x: x, state)  # shallow copy
        from tensorflowonspark_tpu import goodput
        with goodput.ledger().track("checkpoint_save"):
            # the synchronous slice of the save (orbax may commit
            # asynchronously; wait() time lands here too via the same
            # category when callers block on it) — the goodput plane's
            # checkpoint_save badput
            saved = self._mgr.save(step,
                                   args=ocp.args.StandardSave(state),
                                   force=force)
        if saved:
            self._saved_steps.add(step)
            # fault-injection site (chaos.py corrupt_checkpoint=N):
            # garbles the step it just committed so the fallback-restore
            # path is exercisable deterministically; O(1) when unarmed
            from tensorflowonspark_tpu import chaos
            chaos.on_checkpoint_saved(step, self.directory, wait=self.wait)
        return bool(saved)

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, state_like, step=None, fallback=False):
        """Restore into the structure (and shardings) of ``state_like``.

        ``state_like`` is an init-shaped state; when its arrays carry
        shardings (the TP/PP case), orbax restores each process's shards
        in that layout. Returns the restored state, or None if no
        checkpoint exists.

        Cross-mesh restore (elastic resize): ``state_like`` may carry
        shardings over a DIFFERENT mesh shape than the save — e.g. a
        checkpoint saved at data-width N restored onto a width N-1 (or
        N+1) mesh built by ``respec_for_width``. Shardings are
        declarative over mesh axes, so orbax reshards on read; use
        :func:`respec_like` to rebind a template's shardings onto the
        new mesh. The participation contract mirrors :meth:`save`'s:
        under ``jax.distributed`` the restore is a COLLECTIVE — every
        process of the NEW mesh must call ``restore`` with the same
        step and the same (process-uniform) ``state_like`` shardings,
        or the readers deadlock at orbax's barrier; single-process
        restores have no such constraint (all shards are addressable).

        ``fallback=True`` (the recovery posture — supervisor.py's
        RestartFromCheckpoint contract assumes it): when the chosen step
        fails to restore (the classic cause: a writer killed mid-commit
        left a corrupt latest — chaos.py's corrupt_checkpoint injection
        reproduces it), walk back through older steps until one
        restores, instead of wedging the whole recovery on the one bad
        step. The first error is re-raised only when EVERY step fails.
        """
        import orbax.checkpoint as ocp

        if step is not None:
            candidates = [int(step)]
        else:
            candidates = sorted((int(s) for s in self._mgr.all_steps()),
                                reverse=True)
        if not candidates:
            return None
        from tensorflowonspark_tpu import goodput
        first_error = None
        for s in candidates:
            try:
                with goodput.ledger().track("restore"):
                    return self._mgr.restore(
                        s, args=ocp.args.StandardRestore(state_like))
            except Exception as e:  # noqa: BLE001 - orbax raises variously
                if not fallback:
                    raise
                if first_error is None:
                    first_error = e
                logger.warning(
                    "checkpoint step %d failed to restore (%s); "
                    "falling back to the previous step", s, e)
        raise RuntimeError(
            "no checkpoint step under {} could be restored "
            "(tried {})".format(self.directory, candidates)) from first_error

    def wait(self):
        from tensorflowonspark_tpu import goodput
        with goodput.ledger().track("checkpoint_save"):
            # blocking on an async commit is checkpoint badput too
            self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def hook(checkpointer, every_steps=100):
    """Trainer ``train_loop`` hook: save every N steps."""

    def _hook(step_no, state, metrics):
        if step_no % every_steps == 0:
            checkpointer.save(int(state["step"]), state)

    return _hook
