"""KV-block shipping: the wire plane of prefill/decode disaggregation.

PR 17 splits the serving fleet into a prefill tier (compute-bound,
bursty) and a decode tier (bandwidth-bound, steady). This module owns
the bytes between them:

- :func:`pack` / :func:`unpack` — one shipment (the resident prefix
  blocks of one prompt) over the PR 1 frames codec: a pickled meta
  header (token chain, block size, pool dtype, per-block origins,
  source identity + fencing epoch) plus the pool rows of every
  shippable cache leaf as RAW column payloads. On an int8 pool those
  payloads are the codes and per-head scales AS STORED — no dequant
  round-trip, which is both the 3.2x byte win and the bitwise-parity
  guarantee (the decode side splices the exact bytes prefill wrote).
- :func:`ship` — deliver one packed shipment to a decode replica's
  ``POST /kv/splice``: a co-hosted zero-copy path (the frames gather
  straight into a :class:`shm.ShmRing` mapping, with a tiny HTTP
  notify) and a socket path (the frames as one request body). The
  shm path degrades to the socket path whenever the ring is missing,
  full, or too small — shipping is best-effort by design: a failed
  ship costs the decode tier a cold local re-prefill, never a wrong
  answer.

Chaos discipline mirrors the fleet router's ``_http_request``
(fleet.py): the ``chaos.on_net`` verdict is taken BEFORE any bytes
move (request-side loss means the decode side never saw the
shipment), ``drop_response`` delivers the shipment then raises (the
splice HAPPENED but the prefill side must believe it failed — the
duplicate-splice case, which the decode side's resident-chain dedupe
makes idempotent), and ``dup`` re-delivers once, discarding the
second response (the post-timeout retry case).

No serving/fleet imports here — serving.py and fleet.py both import
this module, never the reverse.
"""

import atexit
import http.client
import logging
import threading

import numpy as np

from tensorflowonspark_tpu import chaos, frames, shm

logger = logging.getLogger(__name__)

#: wire-format version stamped into every shipment header; unpack
#: rejects unknown versions loudly instead of misreading raw payloads
WIRE_VERSION = 1

#: seconds a shm-ring write may block before the ship falls back to
#: the socket path (a FULL ring means the consumer is behind — backing
#: off to TCP beats stalling the prefill worker's handler thread)
RING_WRITE_TIMEOUT_S = 0.2


class ShipError(RuntimeError):
    """A shipment could not be delivered (transport-level). The caller
    treats it exactly like a chaos partition: fall back to cold local
    prefill on the decode side, never retry into a double-splice."""


def pack(meta, rows):
    """(meta dict, ``[(path_key, rows_array)]``) -> list of wire buffers.

    ``rows`` is :func:`generation.gather_block_rows` output: one array
    of shape ``[n_blocks, ...]`` per pool leaf, in the LEAF's storage
    dtype. The arrays ride as raw column payloads (zero pickling) of
    one :func:`frames.encode_multi` frame; ``meta`` rides in the
    pickled header. Returns the buffer list ``shm.ShmRing.
    write_buffers`` / the socket sender move verbatim — physical
    transfer cost is exactly :func:`frames.frame_bytes` of it."""
    names = tuple(k for k, _ in rows)
    cols = [np.ascontiguousarray(r) for _, r in rows]
    hdr = dict(meta)
    hdr["v"] = WIRE_VERSION
    hdr["n_blocks"] = int(cols[0].shape[0]) if cols else 0
    return frames.encode_multi(
        [hdr, frames.ColumnarChunk(cols, names=names)])


def unpack(view):
    """One shipment frame (bytes/memoryview) -> ``(meta, rows)``.

    ``rows`` come back as ZERO-COPY views into ``view`` (frames.decode
    semantics): splice synchronously while the source buffer is alive,
    or materialize. Raises ValueError on anything that is not a
    well-formed shipment of this wire version."""
    try:
        obj = frames.decode(view)
    except Exception as e:  # noqa: BLE001 - decode failure modes are
        # open-ended (pickle, struct, slicing) and ALL of them mean
        # the same thing to a splice handler: malformed shipment
        raise ValueError("undecodable KV shipment: {}".format(e))
    if not isinstance(obj, frames.FrameList) or len(obj) != 2:
        raise ValueError("not a KV shipment frame")
    meta, chunk = obj
    if not isinstance(meta, dict) or \
            meta.get("v") != WIRE_VERSION or \
            not isinstance(chunk, frames.ColumnarChunk) or \
            chunk.names is None:
        raise ValueError("malformed KV shipment (wire version {!r})"
                         .format(meta.get("v") if isinstance(meta, dict)
                                 else None))
    return meta, list(zip(chunk.names, chunk.cols))


def split_addr(addr):
    """'host:port' (or a (host, port) pair) -> (host, int port)."""
    if isinstance(addr, (tuple, list)):
        return str(addr[0]), int(addr[1])
    host, _, port = str(addr).rpartition(":")
    return host, int(port)


def _co_hosted(host):
    """True when ``host`` names this machine (loopback): the shm ring
    mapping is reachable, so the zero-copy path applies."""
    return host in ("127.0.0.1", "localhost", "::1")


# -- transport ----------------------------------------------------------
#
# Producer rings are cached per (src, dst) pair and live until process
# exit: one ring serves every shipment between a replica pair, and the
# name embeds this process's pid so shm.sweep_stale can reap them
# after a SIGKILL. Consumer-side opens are cached per name WITH a
# per-ring lock — ShmRing's sequential-consumption contract (at most
# one outstanding read_view) must hold across concurrent /kv/splice
# handler threads.

_rings_lock = threading.Lock()
_producer_rings = {}   # (src, dst) -> ShmRing (created by this process)
_consumer_rings = {}   # name -> (ShmRing, threading.Lock)


def producer_ring(src, dst):
    """Create-or-return this process's ship ring toward ``dst``.
    Raises OSError when the native ring is unavailable."""
    with _rings_lock:
        ring = _producer_rings.get((src, dst))
        if ring is None:
            ring = shm.ShmRing.create(
                shm.kvship_ring_name(src, dst), shm.KVSHIP_CAPACITY)
            _producer_rings[(src, dst)] = ring
        return ring


def consumer_ring(name):
    """Open-or-return the named ship ring plus its consumption lock
    (the decode server serializes read_view/release under it)."""
    with _rings_lock:
        entry = _consumer_rings.get(name)
        if entry is None:
            entry = (shm.ShmRing.open(name), threading.Lock())
            _consumer_rings[name] = entry
        return entry


def close_rings():
    """Close every cached ring (unlinking the ones this process
    created). Tests and engine teardown call this; atexit backstops."""
    with _rings_lock:
        for ring in _producer_rings.values():
            ring.close()
            ring.unlink()
        _producer_rings.clear()
        for ring, _lock in _consumer_rings.values():
            ring.close()
        _consumer_rings.clear()


atexit.register(close_rings)


def _post(host, port, path, body_buffers, headers, timeout):
    """One POST of gathered ``body_buffers`` (no caller-side concat);
    returns (status, body bytes)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        total = sum(memoryview(b).nbytes for b in body_buffers)
        conn.putrequest("POST", path)
        conn.putheader("Content-Type", "application/octet-stream")
        conn.putheader("Content-Length", str(total))
        for k, v in (headers or {}).items():
            conn.putheader(k, v)
        conn.endheaders()
        for b in body_buffers:
            conn.send(bytes(b) if not isinstance(b, (bytes, memoryview))
                      else b)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _deliver(addr, buffers, via, timeout):
    """Move one shipment to ``addr``'s /kv/splice; returns
    (status, body, transport). ``via``: 'auto' / 'shm' / 'socket'."""
    host, port = split_addr(addr)
    if via in ("auto", "shm") and _co_hosted(host) and shm.available():
        try:
            # the frames gather lands straight in the ring mapping; the
            # empty-body notify tells the decode server WHICH ring its
            # one pending message sits in
            ring = producer_ring("local", "{}:{}".format(host, port))
            ring.write_buffers(buffers, timeout=RING_WRITE_TIMEOUT_S)
            status, body = _post(
                host, port, "/kv/splice", [b""],
                {"X-TFOS-KV-Via": "shm", "X-TFOS-KV-Ring": ring.name},
                timeout)
            return status, body, "shm"
        except (OSError, TimeoutError, ValueError) as e:
            if via == "shm":
                raise ShipError("shm ship failed: {}".format(e))
            logger.debug("kvship shm path unavailable (%s); "
                         "falling back to socket", e)
    status, body = _post(host, port, "/kv/splice", buffers, None, timeout)
    return status, body, "socket"


def ship(addr, buffers, src=None, dst=None, via="auto", timeout=30.0):
    """Deliver one packed shipment to ``http://addr/kv/splice``.

    Returns ``(status, body_bytes, transport)`` — 200 means spliced
    (body carries the decode side's block accounting JSON), 409 means
    deliberately rejected (fenced / dtype / pool pressure; body names
    the reason). Raises :class:`chaos.NetPartitioned` under an armed
    partition between ``src`` and ``dst`` and :class:`ShipError` on
    transport failure — both mean "assume not spliced": the decode
    side dedupes resident chains, so a shipment that secretly landed
    costs nothing on retry or fallback."""
    action = None
    if chaos.net_armed():
        # the verdict BEFORE bytes move: request-side loss raises here
        # and the decode side never sees the shipment
        action = chaos.on_net(src=src, dst=dst, response_capable=True)
    try:
        status, body, transport = _deliver(addr, buffers, via, timeout)
    except (OSError, http.client.HTTPException) as e:
        raise ShipError("ship to {} failed: {}".format(addr, e))
    if action == "dup":
        # post-timeout duplicate delivery: re-send once, discard the
        # second response — the splice path must tolerate it (and
        # does: resident-chain dedupe makes a double splice a no-op)
        try:
            _deliver(addr, buffers, via, timeout)
        except (OSError, http.client.HTTPException, ShipError):
            pass
    if action == "drop_response":
        # the shipment LANDED; the response did not — the prefill side
        # must treat it as failed (never report shipped bytes for it)
        raise chaos.NetPartitioned(
            "response from {} dropped".format(dst or addr))
    return status, body, transport
