"""Deterministic fault-injection harness for the supervision plane.

The chaos suite (tests/test_chaos.py, tests/test_recovery.py) and
``bench.py recovery`` need failures that land at an exact, repeatable
moment — "SIGKILL the trainer right after step 3", not "sleep 0.5s and
hope". This module owns that choreography so the kill logic lives in ONE
place with instrumented sites in the framework itself, instead of being
re-derived per test (the load-flakiness source VERDICT r5 flagged).

Arming. Injections are armed by a spec string, either explicitly
(:func:`arm`) or via the ``TFOS_CHAOS`` env var — the env path is how a
driver arms the *trainer* process: the spec rides ``executor_env`` into
the executor and fork/spawn inherits it. Spec grammar::

    point=value[,only=EID][,fuse=PATH][;point2=...]

- ``only=EID`` restricts the injection to the process whose
  ``TFOS_TRAINER_EXECUTOR_ID`` matches (set by node.py's trainer entry)
  — how a 2-executor blacklist test kills executor 1's trainer only.
  Non-numeric values scope by the SITE's caller-supplied identity
  instead: ``only=replica-1`` on a serving point targets one replica's
  engine of an in-process fleet (the engines pass their ``replica_id``
  to :func:`on_decode_step`).
- ``fuse=PATH`` makes the injection single-shot ACROSS process
  incarnations: firing creates the fuse file (content: wall-clock fire
  time), and an existing fuse disarms. A restarted trainer inherits the
  same env, so without a fuse a kill-at-step-N injection would fire
  again on every recovery attempt — fuses are what make
  "kill once, then recover" expressible.

Injection points (each checked at an instrumented framework site):

- ``kill_trainer_at_step=N`` — SIGKILL this process when
  :func:`on_step` sees step >= N (fired by supervision-aware training
  hooks; see supervisor.attach).
- ``kill_trainer_at_batch=N`` — SIGKILL when DataFeed has served N
  non-empty batches (fired by ``DataFeed.next_batch``).
- ``kill_trainer_when_queued=1`` — SIGKILL on the first batch served
  while this trainer holds an UNCONSUMED EndPartition marker (the
  value is grammar-required but unused): the marker rides the feeder's
  final put, so holding it proves the feeder finished writing and is
  parked in its queue join on the owed task_done — the kill provably
  lands in the join-park window, never mid-write. Queue transport
  only; needs batch_size < the final chunk's record count (a batch
  that consumes the marker in-call settles the join before the hook
  runs, no kill fires, and the caller's positive assertion fails
  loudly instead of flaking).
- ``stall_consumer_for=T`` (alias ``stall_ring_slot``) — the consumer
  sleeps T seconds once, holding whatever ring slots its pending
  segments pin: the producer wedges on ring space and the feed progress
  counter freezes while the trainer stays alive — the ring-wedge
  signature the supervisor classifies.
- ``drop_heartbeats_for=T`` — suppress heartbeat publishing (DataFeed's
  feed_hb AND node.py's reservation beats) for T seconds from the first
  suppressed attempt: lets tests drive executor-lost detection without
  killing anything.
- ``corrupt_checkpoint=N`` — after ``Checkpointer.save`` commits step N,
  garble every file of that step on disk (fired by checkpoint.py); the
  restore-with-fallback path is the recovery under test.

Network fault plane (PR 12 — fired at the transport sites
``fleet._http_request`` wraps around every router<->replica exchange and
``reservation.MessageSocket.send`` wraps around every reservation
message, via :func:`on_net`). Process faults kill things; these break
the WIRES between healthy processes, which is where ambiguous timeouts
— "did the request execute before the response was lost?" — come from.
Endpoint scoping uses ``SRC:DST`` pairs (either side ``*``): the router
dispatches as ``router:<replica_id>``, a replica/executor beats as
``<id>:reservation``. Sites that pass no identity at all match only
fully-wildcarded (``*:*`` / unscoped) injections.

- ``net_drop=P[,only=SRC:DST][,seed=N][,for=T]`` — each matching
  exchange independently fails with probability P (a seeded
  ``random.Random(seed)`` draw — the k-th matching exchange consumes
  the k-th draw, so a given seed yields the same drop schedule every
  run; ``P=1`` is the deterministic always-drop). The failure is
  :class:`NetPartitioned` (a ``ConnectionError``): the caller cannot
  tell whether the peer saw the request — exactly the ambiguity
  idempotent dispatch exists for.
- ``net_delay=T[,only=SRC:DST][,for=W]`` — every matching exchange is
  delayed T seconds before it starts: the gray-replica signature
  (alive, beating, SLOW) hedged requests exist for.
- ``net_dup=P[,only=SRC:DST][,seed=N][,for=T]`` — each matching
  exchange is DUPLICATED with seeded probability P: the transport
  delivers the same request twice (the duplicate's response is
  discarded). The replica-side dedup window is the behavior under
  test — without it a duplicated ``:generate`` decodes twice. HTTP
  transport only: ``MessageSocket`` is a framed request/response TCP
  stream, where the transport cannot duplicate a frame (and injecting
  one would desynchronize the protocol, not model a network fault) —
  that site ignores the dup action.
- ``net_partition=SRC:DST,for=T`` — from the first matched exchange,
  the SRC->DST link is DOWN for T seconds (every matching exchange
  raises :class:`NetPartitioned`); after T the partition HEALS and the
  injection is spent — re-arm for another flap. ``for=`` is mandatory:
  a partition that never heals is just a drop, and the heal is the
  moment split-brain fencing and retry dedup get tested. The OPENING
  exchange (in flight when the link died) loses only its RESPONSE on
  transports that can tell the difference — the request was delivered
  and executed, the caller just never learns it (see :func:`on_net`).
- ``drop_executor_then_return_after=T`` — EXECUTOR loss, not trainer
  crash: at the scoped trainer's first :func:`on_step` site, SIGKILL
  the whole executor process (the trainer's parent) and then this
  trainer — the engine sees the connection die and the heartbeat lease
  expires, the executor-lost signature the ElasticResize policy
  shrinks on. The value T is the RETURN delay: the driver side pairs
  the injection with :func:`schedule_executor_return`, which watches
  the fuse (mandatory for this point — a dropped executor must not
  re-fire in its revived incarnation) and revives the executor T
  seconds after the recorded fire time, so "capacity returns" is as
  deterministic as the drop. ``only=EID`` scoping is effectively
  required too: an unscoped drop would take down every executor at
  once.

Serving-plane points (PR 4 — fired at serving.DecodeEngine's
instrumented sites, so the request-lifecycle story is deterministically
testable):

- ``kill_scheduler_at_step=N`` — raise :class:`SchedulerKilled` inside
  the decode scheduler loop once N decode steps completed: the thread
  dies exactly as an uncaught device error would kill it (threads have
  no SIGKILL; an in-loop raise is the faithful equivalent), outstanding
  handles fail retriable, and the supervisor's RestartEngine policy is
  the recovery under test.
- ``stall_decode_for=T`` — the scheduler sleeps T seconds once at a
  step boundary: in-flight deadlines expire while the engine stays
  alive — the slow-replica signature deadline eviction exists for.
- ``disconnect_client_at_token=N`` — the first request to reach N
  emitted tokens is cancelled as if its client disconnected
  mid-stream; the step-boundary slot-free path is the behavior under
  test.
- ``kill_serving_executor_at_request=K,only=<replica_id>,fuse=PATH`` —
  whole-EXECUTOR loss on the serving plane (PR 13): once the scoped
  replica's engine has seen K requests submitted, SIGKILL the executor
  process hosting it (the engine runs IN the executor for
  executor-hosted fleets). The lease expires, the router down-marks,
  and the autoscaler's replacement spawn is the recovery under test.
  ``fuse`` is mandatory (the replacement replica inherits the victim's
  replica_id AND the armed executor_env spec); pair with
  :func:`schedule_executor_return` for deterministic capacity return.

Every fire is logged loudly. All checks are O(1) dict lookups when
nothing is armed, so instrumented sites cost nothing in production.
"""

import logging
import os
import random
import signal
import threading
import time

logger = logging.getLogger(__name__)

ENV_VAR = "TFOS_CHAOS"

#: transport-level points (the network fault plane, PR 12)
NET_POINTS = ("net_drop", "net_delay", "net_dup", "net_partition")

#: spec keys that accept the generic grammar above
POINTS = ("kill_trainer_at_step", "kill_trainer_at_batch",
          "kill_trainer_when_queued", "stall_consumer_for",
          "stall_ring_slot", "drop_heartbeats_for", "corrupt_checkpoint",
          "kill_scheduler_at_step", "stall_decode_for",
          "disconnect_client_at_token", "drop_executor_then_return_after",
          "kill_serving_executor_at_request",
          "kill_reservation_server", "kill_router_at_request",
          "restart_reservation_after"
          ) + NET_POINTS


class SchedulerKilled(RuntimeError):
    """kill_scheduler_at_step fired: the decode scheduler thread dies
    by raising this (the thread-level analog of SIGKILL — threads
    cannot be signalled, and any uncaught raise kills the loop the
    same way a real device error does)."""


class NetPartitioned(ConnectionError):
    """A net_drop / net_partition injection ate this transport
    exchange. Deliberately a ``ConnectionError``: every caller's
    existing connection-failure handling (beat retry, router failover,
    lease expiry) must treat an injected network fault EXACTLY like a
    real one — no chaos-aware special cases to go stale in."""


class Injection(object):
    """One armed injection point."""

    __slots__ = ("point", "value", "only", "fuse", "fired", "started",
                 "window", "seed", "endpoints", "_rng")

    def __init__(self, point, value, only=None, fuse=None, window=None,
                 seed=None, endpoints=None):
        self.point = point
        self.value = value
        self.only = only
        self.fuse = fuse
        self.fired = False
        self.started = None  # for duration-window points
        #: ``for=T`` — seconds the effect lasts from its first matched
        #: check; None = no window (single-shot points keep their own
        #: semantics, net drop/delay/dup apply until disarm)
        self.window = window
        #: ``seed=N`` — the probability schedule's RNG seed (net_drop /
        #: net_dup); a fixed seed makes the k-th matching exchange's
        #: draw identical across runs
        self.seed = seed
        #: (src, dst) endpoint pattern for net points (either may be
        #: ``"*"``); None matches every instrumented site
        self.endpoints = endpoints
        self._rng = None

    @property
    def rng(self):
        """Seeded per-injection RNG (lazily built): the deterministic
        draw schedule behind probabilistic net points."""
        if self._rng is None:
            self._rng = random.Random(0 if self.seed is None
                                      else self.seed)
        return self._rng

    def matches_net(self, src, dst):
        """Endpoint scoping for transport sites: ``only=SRC:DST`` (or
        net_partition's value) against the site's identities. A site
        that passes None for a side only matches ``*`` on that side —
        an unlabeled transport can never be caught by a scoped spec."""
        if self.endpoints is None:
            return True
        esrc, edst = self.endpoints
        src_ok = esrc == "*" or (src is not None and str(src) == esrc)
        dst_ok = edst == "*" or (dst is not None and str(dst) == edst)
        return src_ok and dst_ok

    def in_window(self):
        """True while inside the ``[first match, +for)`` effect window
        (no ``for=`` means always, once matched). The window opens at
        the FIRST matched check and the injection is marked spent at
        expiry — how ``net_partition`` heals deterministically."""
        if self.window is None:
            return True
        now = time.monotonic()
        if self.started is None:
            self.started = now
            logger.warning("CHAOS %s window open for %gs", self.point,
                           self.window)
        if now - self.started < self.window:
            return True
        if not self.fired:
            self.mark_fired()
            logger.warning("CHAOS %s window expired (healed)", self.point)
        return False

    def ready(self, ident=None):
        """Armed, not yet fired, fuse intact, and scoped to this process
        (or, for multi-replica sites sharing one process, to the
        caller-supplied ``ident`` — how a fleet test kills ONE replica's
        scheduler when every replica's engine runs in the same
        process)."""
        if self.fired:
            return False
        if self.fuse and os.path.exists(self.fuse):
            return False
        if self.only is not None:
            if ident is not None and str(ident) == str(self.only):
                return True
            eid = os.environ.get("TFOS_TRAINER_EXECUTOR_ID")
            try:
                return eid is not None and int(eid) == int(self.only)
            except (TypeError, ValueError):
                return False
        return True

    def mark_fired(self):
        self.fired = True
        if self.fuse:
            try:
                with open(self.fuse, "x") as f:
                    f.write(repr(time.time()))
            except FileExistsError:
                pass


_lock = threading.Lock()
_explicit = None   # spec armed via arm(); wins over the env
_parsed_for = object()  # spec string the cache below was parsed from
_injections = {}


def parse_spec(spec):
    """Spec string -> {point: Injection}; raises ValueError on bad specs
    (a typo'd chaos spec must fail the test loudly, not silently not
    inject)."""
    out = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields = entry.split(",")
        if "=" not in fields[0]:
            raise ValueError("chaos entry %r needs point=value" % entry)
        point, value = fields[0].split("=", 1)
        point = point.strip()
        if point not in POINTS:
            raise ValueError("unknown chaos point %r (known: %s)"
                             % (point, ", ".join(POINTS)))
        if point == "stall_ring_slot":  # alias
            point = "stall_consumer_for"
        only = fuse = window = seed = endpoints = None
        for field in fields[1:]:
            if "=" not in field:
                raise ValueError("chaos field %r needs key=value" % field)
            k, v = field.split("=", 1)
            k = k.strip()
            if k == "only":
                if point in NET_POINTS:
                    # net scoping is an endpoint pair, not a process id
                    endpoints = _parse_endpoints(point, v)
                    continue
                # numeric executor ids stay ints (the TFOS_TRAINER_
                # EXECUTOR_ID scoping); anything else is a replica
                # ident matched against the site's caller-supplied id
                try:
                    only = int(v)
                except ValueError:
                    only = v.strip()
            elif k == "fuse":
                fuse = v
            elif k == "for":
                try:
                    window = float(v)
                except ValueError:
                    raise ValueError(
                        "chaos field for=%r must be seconds" % v)
            elif k == "seed":
                try:
                    seed = int(v)
                except ValueError:
                    raise ValueError(
                        "chaos field seed=%r must be an integer" % v)
            else:
                raise ValueError("unknown chaos field %r" % k)
        if point == "net_partition":
            # the VALUE is the partitioned link (src:dst); for= is the
            # outage duration and is mandatory — a partition that never
            # heals is just net_drop, and the HEAL is the moment the
            # fencing/dedup behavior under test actually runs
            endpoints = _parse_endpoints(point, value)
            if window is None:
                raise ValueError(
                    "net_partition requires for=T (the heal time); "
                    "use net_drop for a permanent fault")
            value = "0"
        if point in NET_POINTS:
            out[point] = Injection(point, float(value), only=only,
                                   fuse=fuse, window=window, seed=seed,
                                   endpoints=endpoints)
            continue
        if window is not None or seed is not None:
            raise ValueError(
                "chaos fields for=/seed= only apply to net points "
                "({}), not {}".format(", ".join(NET_POINTS), point))
        if point == "kill_serving_executor_at_request" and not fuse:
            # same load-bearing fuse as drop_executor: the spec rides
            # executor_env into EVERY executor incarnation, and the
            # autoscaler's replacement replica keeps the victim's
            # replica_id — without a fuse the replacement (or a revived
            # executor) re-fires at the same request count forever
            raise ValueError(
                "kill_serving_executor_at_request requires fuse=PATH "
                "(the kill must be single-shot across executor "
                "incarnations — the replacement replica inherits both "
                "the armed spec and the victim's replica_id)")
        if point == "drop_executor_then_return_after" and not fuse:
            # the fuse is load-bearing here, not just single-shot
            # bookkeeping: the spec rides executor_env into every
            # incarnation, so a revived executor would re-fire the
            # drop forever, and the driver-side return scheduler
            # reads the fire time from the fuse file
            raise ValueError(
                "drop_executor_then_return_after requires fuse=PATH "
                "(the drop must be single-shot across incarnations "
                "and the fuse carries the fire time the return "
                "scheduler needs)")
        out[point] = Injection(point, float(value), only=only, fuse=fuse)
    return out


def _parse_endpoints(point, raw):
    """``SRC:DST`` -> (src, dst); either side may be ``*``."""
    parts = str(raw).strip().split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        raise ValueError(
            "{} endpoints must be SRC:DST (either side may be '*'), "
            "got {!r}".format(point, raw))
    return parts[0], parts[1]


def arm(spec):
    """Arm this process explicitly (tests); overrides the env spec."""
    global _explicit, _parsed_for
    with _lock:
        _explicit = spec
        _parsed_for = object()  # invalidate cache


def disarm():
    """Drop the explicit spec and any fired-state; the process follows
    the ``TFOS_CHAOS`` env var again (unset it too for a clean slate —
    the test fixture does)."""
    global _explicit, _parsed_for
    with _lock:
        _explicit = None
        _parsed_for = object()


def _current():
    """{point: Injection} for the active spec, cached per spec value."""
    global _parsed_for, _injections
    spec = _explicit if _explicit is not None else os.environ.get(ENV_VAR)
    with _lock:
        if spec != _parsed_for:
            _injections = parse_spec(spec) if spec else {}
            _parsed_for = spec
        return _injections


def armed(point, ident=None):
    """The ready :class:`Injection` for ``point``, else None.
    ``ident`` scopes multi-replica sites: an ``only=<ident>`` injection
    fires only when the calling site passes a matching identity."""
    if point == "stall_ring_slot":
        point = "stall_consumer_for"
    inj = _current().get(point)
    return inj if inj is not None and inj.ready(ident) else None


def _kill_self(inj, why):
    logger.error("CHAOS firing %s (%s): SIGKILL pid %d",
                 inj.point, why, os.getpid())
    inj.mark_fired()
    os.kill(os.getpid(), signal.SIGKILL)


# -- instrumented-site hooks ----------------------------------------------

def on_step(step):
    """Training-step site (supervision hooks call this after the step —
    and its checkpoint — committed, so a kill-at-step-N leaves step N
    restorable)."""
    inj = armed("kill_trainer_at_step")
    if inj is not None and step >= inj.value:
        _kill_self(inj, "step %d >= %g" % (step, inj.value))
    inj = armed("drop_executor_then_return_after")
    if inj is not None:
        _drop_executor(inj, step)


def _drop_executor(inj, step):
    """Fire drop_executor_then_return_after: SIGKILL the executor
    process (this trainer's parent) and then this trainer — whole-node
    loss, landing at the step site so the just-committed step stays
    restorable. Refuses outside a trainer process: the parent of
    anything else (a pytest runner, say) is not an executor."""
    if os.environ.get("TFOS_TRAINER_EXECUTOR_ID") is None:
        raise RuntimeError(
            "drop_executor_then_return_after can only fire inside a "
            "trainer process (its parent is the executor to drop); "
            "this process has no TFOS_TRAINER_EXECUTOR_ID")
    ppid = os.getppid()
    logger.error("CHAOS firing drop_executor_then_return_after at step "
                 "%s: SIGKILL executor pid %d then trainer pid %d "
                 "(capacity should return %gs after the fuse time)",
                 step, ppid, os.getpid(), inj.value)
    inj.mark_fired()
    if ppid > 1:  # orphaned trainer: the executor is already gone
        os.kill(ppid, signal.SIGKILL)
    os.kill(os.getpid(), signal.SIGKILL)


def on_batch(feed, batches_served):
    """DataFeed site, after each non-empty batch is assembled."""
    inj = armed("kill_trainer_at_batch")
    if inj is not None and batches_served >= inj.value:
        _kill_self(inj, "batch %d >= %g" % (batches_served, inj.value))
    inj = armed("kill_trainer_when_queued")
    if inj is not None:
        if getattr(feed, "_queue_in", None) is None:
            raise RuntimeError(
                "kill_trainer_when_queued needs the queue transport "
                "(the ring has no join to park in)")

        # The ONE provable "feeder finished writing, its join is
        # blocked on this trainer" event: this trainer holds the
        # partition's EndPartition marker UNCONSUMED (in the decode
        # backlog). The marker always rides the feeder's final put
        # (tail coalescing frames it with the last chunk), so holding
        # it proves every put of the partition completed — the kill
        # cannot land mid-write — and its pending task_done proves the
        # feeder's join is still blocked. Queue depth proves neither:
        # queued items can be mid-partition chunks with the feeder
        # still writing behind them (the mid-put race this harness
        # exists to eliminate). Checked per batch, NOT polled: the
        # backlog only advances when this consumer consumes, so on a
        # multi-chunk partition the marker arrives on a later
        # next_batch call. Needs batch_size < the final chunk's record
        # count (otherwise the same call consumes the marker and fires
        # its task_done before this hook runs — no kill ever fires,
        # and the caller's positive assertion fails loudly).
        from tensorflowonspark_tpu import marker as marker_mod
        if any(isinstance(item, marker_mod.Marker)
               for item in feed._backlog):
            _kill_self(inj, "holding an unconsumed EndPartition marker "
                            "(feeder parked in its join)")
    inj = armed("stall_consumer_for")
    if inj is not None:
        inj.mark_fired()
        logger.warning("CHAOS stalling consumer for %gs "
                       "(ring slots stay pinned)", inj.value)
        time.sleep(inj.value)


def on_decode_step(steps_done, ident=None):
    """Decode-scheduler site (serving.DecodeEngine._loop), called at
    each step boundary with the number of COMPLETED decode steps.
    ``stall_decode_for`` sleeps here (once); ``kill_scheduler_at_step``
    raises :class:`SchedulerKilled` once ``steps_done`` reaches N.
    ``ident`` is the engine's replica id: an ``only=<replica_id>``
    injection targets ONE replica of an in-process fleet."""
    inj = armed("stall_decode_for", ident)
    if inj is not None:
        inj.mark_fired()
        logger.warning("CHAOS stalling decode scheduler for %gs",
                       inj.value)
        time.sleep(inj.value)
    inj = armed("kill_scheduler_at_step", ident)
    if inj is not None and steps_done >= inj.value:
        inj.mark_fired()
        logger.error("CHAOS firing kill_scheduler_at_step "
                     "(step %d >= %g, replica %s): killing the decode "
                     "scheduler", steps_done, inj.value, ident)
        raise SchedulerKilled(
            "chaos: decode scheduler killed at step {}".format(steps_done))


def on_serving_request(requests_seen, ident=None):
    """Serving-admission site (serving.DecodeEngine._submit_many),
    called with the cumulative number of requests this engine has seen
    submitted. ``kill_serving_executor_at_request=K,only=<replica_id>``
    SIGKILLs the WHOLE executor process hosting the replica once the
    K-th request arrives — executor loss at a deterministic point in
    the serving stream, the signature the autoscaler's replacement path
    (lease expiry -> router down-mark -> replacement spawn) recovers
    from. Refuses outside an executor-hosted serving node: the process
    about to die must actually BE an executor (node.serve_replica sets
    the marker env), not a driver-placement test process that merely
    armed the spec."""
    inj = armed("kill_serving_executor_at_request", ident)
    if inj is None or requests_seen < inj.value:
        return
    if os.environ.get("TFOS_SERVING_EXECUTOR_ID") is None:
        raise RuntimeError(
            "kill_serving_executor_at_request can only fire inside an "
            "executor-hosted serving node (node.serve_replica sets "
            "TFOS_SERVING_EXECUTOR_ID); this process is not one — "
            "scope the injection with only=<replica_id> or arm it via "
            "executor_env")
    _kill_self(inj, "serving request %d >= %g on replica %s"
               % (requests_seen, inj.value, ident))


def on_token(tokens_emitted):
    """Token-delivery site (serving.DecodeEngine._deliver); True means
    'this request's client just disconnected' — the engine cancels the
    request and the step-boundary eviction frees its slot. Fires once,
    on the first request to reach N emitted tokens."""
    inj = armed("disconnect_client_at_token")
    if inj is None or tokens_emitted < inj.value:
        return False
    inj.mark_fired()
    logger.warning("CHAOS disconnect_client_at_token: simulating client "
                   "disconnect after %d tokens", tokens_emitted)
    return True


def on_reservation_beat(beats_seen):
    """Reservation-server BEAT site (reservation.Server._handle),
    called with the cumulative BEAT messages this server has handled.
    ``kill_reservation_server=N`` returns True once the N-th beat
    lands; the server then CRASHES in place (``Server.crash()`` — the
    in-process SIGKILL emulation: lease-table state already written,
    the reply never sent), which is the control-plane mirror of
    ``kill_serving_executor_at_request``. Single-shot: the in-process
    ``fired`` latch survives the server's restart (same process), so
    the restarted server is never re-killed at the same beat count."""
    inj = armed("kill_reservation_server")
    if inj is None or beats_seen < inj.value:
        return False
    inj.mark_fired()
    logger.error("CHAOS kill_reservation_server: crashing the "
                 "reservation server at BEAT %d >= %g",
                 beats_seen, inj.value)
    return True


def on_router_request(requests_seen, ident=None):
    """Fleet-router dispatch site (fleet.FleetRouter.dispatch), called
    with the cumulative dispatches this router has seen.
    ``kill_router_at_request=K`` returns True once the K-th dispatch
    arrives; the router then CRASHES (listener closed mid-traffic, no
    drain) — leader death at a deterministic point in the request
    stream, the signature the warm-standby takeover e2e recovers
    from. ``ident`` is the router's model name: ``only=<name>`` kills
    ONE router when a leader and standby share the process."""
    inj = armed("kill_router_at_request", ident)
    if inj is None or requests_seen < inj.value:
        return False
    inj.mark_fired()
    logger.error("CHAOS kill_router_at_request: crashing router %s at "
                 "dispatch %d >= %g", ident, requests_seen, inj.value)
    return True


def on_heartbeat():
    """Heartbeat-publish sites; True = suppress this publish.

    The suppression window is [first suppressed attempt, +T seconds);
    after it expires the injection is spent and heartbeats resume.
    """
    inj = armed("drop_heartbeats_for")
    if inj is None:
        return False
    if inj.started is None:
        inj.started = time.monotonic()
        logger.warning("CHAOS dropping heartbeats for %gs", inj.value)
    if time.monotonic() - inj.started < inj.value:
        return True
    inj.mark_fired()
    return False


def on_net(src=None, dst=None, response_capable=False):
    """Transport-exchange site (the network fault plane). Called once
    per exchange by the instrumented transports — ``fleet.
    _http_request`` (router<->replica HTTP) and ``reservation.
    MessageSocket.send`` (reservation messages, beats included) — with
    the exchange's endpoint identities.

    Effects, in precedence order: an ACTIVE ``net_partition`` window or
    a ``net_drop`` draw loses the exchange; ``net_delay`` sleeps before
    the exchange runs; ``net_dup`` returns ``"dup"``, telling the
    transport to deliver the exchange TWICE (the caller discards the
    duplicate's response). Returns None when nothing fires. O(1) dict
    lookups when no net point is armed.

    A LOST exchange has two faces, and the difference is the whole
    point of idempotent dispatch: request-side loss (the peer never saw
    it) raises :class:`NetPartitioned` before any bytes move;
    response-side loss — the peer EXECUTED the request, only the answer
    died on the wire — returns ``"drop_response"``, telling a
    ``response_capable`` transport to run the exchange, discard the
    response, and raise. Sites that can't separate the two (a one-way
    message send) pass ``response_capable=False`` and get request-side
    loss only. Deterministic choreography: a ``net_partition``'s
    OPENING exchange is response-side (it was in flight when the link
    died — the classic ambiguous timeout), the rest of the window is
    request-side (the link is known down); ``net_drop`` draws the side
    from the same seeded RNG as the drop itself (50/50), so a fixed
    seed fixes the whole schedule."""
    cur = _current()
    inj = cur.get("net_partition")
    if inj is not None and not inj.fired and inj.matches_net(src, dst):
        opening = inj.started is None
        if inj.in_window():
            if opening and response_capable:
                logger.warning(
                    "CHAOS net_partition: %s -> %s opening exchange "
                    "loses its RESPONSE (request delivered)", src, dst)
                return "drop_response"
            raise NetPartitioned(
                "chaos net_partition: {} -> {} is partitioned".format(
                    src, dst))
    inj = cur.get("net_drop")
    if inj is not None and not inj.fired and inj.matches_net(src, dst) \
            and inj.in_window() and inj.rng.random() < inj.value:
        if response_capable and inj.rng.random() < 0.5:
            logger.warning("CHAOS net_drop: %s -> %s loses its "
                           "RESPONSE (request delivered)", src, dst)
            return "drop_response"
        logger.warning("CHAOS net_drop: dropping %s -> %s exchange",
                       src, dst)
        raise NetPartitioned(
            "chaos net_drop: {} -> {} exchange lost".format(src, dst))
    inj = cur.get("net_delay")
    if inj is not None and not inj.fired and inj.matches_net(src, dst) \
            and inj.in_window():
        time.sleep(inj.value)
    inj = cur.get("net_dup")
    if inj is not None and not inj.fired and inj.matches_net(src, dst) \
            and inj.in_window() and inj.rng.random() < inj.value:
        logger.warning("CHAOS net_dup: duplicating %s -> %s exchange",
                       src, dst)
        return "dup"
    return None


def net_armed():
    """True when any net point is armed (transports use this to skip
    per-exchange bookkeeping entirely in production)."""
    cur = _current()
    return any(p in cur for p in NET_POINTS)


def on_checkpoint_saved(step, directory, wait=None):
    """Checkpointer site, after a successful save of ``step``."""
    inj = armed("corrupt_checkpoint")
    if inj is None or int(step) != int(inj.value):
        return
    if wait is not None:
        wait()  # the async commit must land before we can garble it
    inj.mark_fired()
    corrupt_step(directory, int(step))


# -- harness utilities (tests share these instead of re-rolling them) ------

def poll_until(predicate, timeout, interval=0.05):
    """Event/deadline polling: True when ``predicate()`` held within
    ``timeout`` seconds, False on expiry. The one wait primitive the
    chaos suite uses — never a bare fixed sleep."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return bool(predicate())


def kill_when(get_pid, trigger, settle=0.5, deadline=60, sig=signal.SIGKILL):
    """Background assassin: once ``trigger()`` holds, wait ``settle``
    seconds (a floor for in-flight work, not a race-prone deadline) and
    send ``sig`` to ``get_pid()``. Returns the started thread; a missed
    trigger means no kill ever fires — the caller's positive assertion
    then fails loudly rather than flakily."""

    def _assassin():
        if not poll_until(trigger, timeout=deadline, interval=0.1):
            logger.warning("chaos.kill_when trigger never held; not firing")
            return
        time.sleep(settle)
        try:
            pid = get_pid()
            logger.error("CHAOS kill_when: sending %s to pid %d", sig, pid)
            os.kill(pid, sig)
        except (OSError, ValueError) as e:
            logger.warning("chaos.kill_when could not fire: %s", e)

    t = threading.Thread(target=_assassin, name="chaos-assassin",
                         daemon=True)
    t.start()
    return t


def schedule_executor_return(sc, executor_id, fuse, delay=None,
                             deadline=120):
    """Driver-side half of ``drop_executor_then_return_after``: wait for
    the fuse file (its content is the drop's wall-clock fire time),
    sleep until ``fire_time + delay``, then revive the executor via
    ``sc.revive_executor`` — deterministic "capacity returns" for the
    elastic-regrow suite. ``delay`` defaults to the injection armed IN
    THIS (driver) process; when the spec rides ``executor_env`` only —
    the usual arrangement — this process has no armed injection, so
    pass ``delay`` explicitly (a loud warning and delay 0 otherwise).
    Returns the started thread; a drop that never fires means no
    revival, and the caller's positive assertion (formations, width
    history) fails loudly instead of flaking."""
    if delay is None:
        inj = _current().get("drop_executor_then_return_after")
        if inj is None:
            logger.warning(
                "schedule_executor_return: no drop_executor_then_"
                "return_after armed in THIS process (the spec likely "
                "rides executor_env) — defaulting delay to 0; pass "
                "delay= explicitly for a deterministic return time")
            delay = 0.0
        else:
            delay = inj.value

    def _returner():
        if not poll_until(lambda: os.path.exists(fuse), timeout=deadline,
                          interval=0.05):
            logger.warning("chaos.schedule_executor_return: fuse %s "
                           "never appeared; not reviving", fuse)
            return
        try:
            fired_at = float(open(fuse).read())
        except (OSError, ValueError):
            fired_at = time.time()
        wait = fired_at + float(delay) - time.time()
        if wait > 0:
            time.sleep(wait)
        try:
            logger.warning("CHAOS returning executor %s (capacity back "
                           "%.2fs after the drop)", executor_id,
                           time.time() - fired_at)
            sc.revive_executor(executor_id)
        except Exception as e:  # noqa: BLE001 - harness must not raise
            logger.warning("chaos.schedule_executor_return failed: %s", e)

    t = threading.Thread(target=_returner, name="chaos-returner",
                         daemon=True)
    t.start()
    return t


def schedule_reservation_restart(fleet, delay=None, deadline=60):
    """Driver-side half of ``kill_reservation_server``: wait for the
    fleet's reservation server to die (its ``done`` latch — the crash
    site sets it), sleep ``delay`` seconds of headless time, then
    restart it via ``fleet.restart_reservation()`` — deterministic
    "the driver comes back" for the control-plane recovery suite.
    ``delay`` defaults to the armed ``restart_reservation_after``
    injection's value (0 when none is armed). Returns the started
    thread; a kill that never fires means no restart, and the
    caller's positive assertions (zero failures, floors restored)
    fail loudly instead of flaking."""
    if delay is None:
        inj = _current().get("restart_reservation_after")
        delay = float(inj.value) if inj is not None else 0.0

    def _restarter():
        if not poll_until(lambda: fleet.reservation.done.is_set(),
                          timeout=deadline, interval=0.02):
            logger.warning("chaos.schedule_reservation_restart: the "
                           "reservation server never died; not "
                           "restarting")
            return
        if delay > 0:
            time.sleep(delay)
        inj = _current().get("restart_reservation_after")
        if inj is not None:
            inj.mark_fired()
        try:
            logger.warning("CHAOS restarting the reservation server "
                           "(%.2fs of headless time)", delay)
            fleet.restart_reservation()
        except Exception as e:  # noqa: BLE001 - harness must not raise
            logger.warning("chaos.schedule_reservation_restart "
                           "failed: %s", e)

    t = threading.Thread(target=_restarter, name="chaos-resv-restarter",
                         daemon=True)
    t.start()
    return t


def latest_step_on_disk(directory):
    """Largest integer-named step dir under an orbax checkpoint root
    (filesystem view only — usable from processes that must not import
    jax/orbax, like the driver-side supervisor)."""
    try:
        steps = [int(name) for name in os.listdir(directory)
                 if name.isdigit()]
    except OSError:
        return None
    return max(steps) if steps else None


def corrupt_step(directory, step):
    """Garble every regular file of checkpoint ``step`` in place
    (overwrite leading bytes + truncate): a restore of this step must
    fail, which is exactly what the fallback-restore path recovers
    from. Returns the number of files corrupted."""
    step_dir = os.path.join(directory, str(step))
    count = 0
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.write(b"\xde\xad\xbe\xef" * 4)
                    f.truncate(max(16, size // 2))
                count += 1
            except OSError:
                continue
    logger.warning("CHAOS corrupted checkpoint step %s under %s "
                   "(%d files)", step, directory, count)
    return count


def corrupt_latest_checkpoint(directory):
    """Corrupt the newest step under ``directory``; returns that step
    (None when the root holds no checkpoints)."""
    step = latest_step_on_disk(directory)
    if step is None:
        return None
    corrupt_step(directory, step)
    return step
