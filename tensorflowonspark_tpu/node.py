"""Node runtime: the executor-side heart of the framework.

Reference: ``tensorflowonspark/TFSparkNode.py`` (SURVEY.md §2 "Node
runtime", §3.1/§3.2 call stacks). One bootstrap task runs per executor and:
derives the node ordinal, starts the per-node queue broker, binds the
accelerator, reserves the ports the node will serve on, registers with the
driver's reservation barrier, blocks until the whole cluster is formed,
then runs the user ``map_fun`` — in a background process for
``InputMode.SPARK`` (the queue-fed path) or inline for
``InputMode.TENSORFLOW`` (direct file reads).

TPU-native differences from the reference:

- **No GPU-grab race.** The reference's ``gpu_info.get_gpus`` parses
  ``nvidia-smi`` and retries when concurrent executors steal devices; on a
  TPU host the chips belong to whichever single process initializes the
  runtime, so "device pinning" here means: the *trainer* process (spawned
  below) owns the TPU, and this bootstrap/feeder process must never import
  jax (SURVEY.md §7.3 "Background process + libtpu").
- **TF_CONFIG → JAX coordination.** Instead of exporting ``TF_CONFIG`` for
  a TF gRPC server mesh, the barrier's sorted node list yields
  ``process_id`` (= sorted index) and the chief's reserved port becomes the
  ``jax.distributed.initialize`` coordinator address. The trainer process
  reads these from env (``TFOS_*`` variables below).
- **Chunked feed.** Feed tasks batch records into chunks before the queue
  ``put`` — the reference's per-record manager-proxy round trip is its
  documented bottleneck (SURVEY.md §3.2 hot loop) and is not reproduced.
  Chunks are size-targeted (FEED_FRAME_BYTES) so tiny records coalesce
  into full frames, and on the ring a partition's tail chunk rides one
  message with its EndPartition marker — the per-message fixed costs the
  small-batch regime otherwise pays per chunk.
"""

import logging
import multiprocessing
import os
import queue as _queue
import subprocess
import sys
import threading
import time

from tensorflowonspark_tpu import manager, marker, reservation, util
from tensorflowonspark_tpu.datafeed import DataFeed

logger = logging.getLogger(__name__)

#: Chunk size for the feed plane when record byte sizes are unknowable
#: (object/ragged records): records per queue item, tuned for pickling
#: cost, not device batch size — DataFeed re-slices. All-ndarray records
#: get size-targeted chunks instead (FEED_FRAME_BYTES below).
FEED_CHUNK = 256

#: Byte target per transport frame for measurable (all-ndarray) records:
#: tiny records coalesce into frames of about this size so per-message
#: fixed costs (frame-header pickling, ring wakeups, slot bookkeeping)
#: amortize across many records — the bulk regime gets that amortization
#: for free from its ~38MB frames; the small-batch regime pays the fixed
#: costs on every chunk unless the feeder packs more records per frame.
#: Env-tunable: TFOS_FEED_FRAME_BYTES.
FEED_FRAME_BYTES = 4 * 1024 * 1024

#: Hard cap on records per chunk regardless of the byte target: bounds
#: the feeder's stacking latency for minuscule records (an unbounded
#: target would stall the trainer's first batch behind a whole-partition
#: stack).
FEED_CHUNK_MAX = 4096

#: Per-executor node state, set by the bootstrap task and read by the
#: feed/shutdown tasks that later run in the same executor process
#: (reference: executor_id file + ``_get_manager`` reconnect).
_NODE_STATE = {}


def _state():
    """The live per-process node state dict — ALWAYS use this in closures.

    The closures returned by ``run``/``train``/``inference``/``shutdown``
    are nested functions, so cloudpickle ships them to executors BY VALUE
    and copies referenced module globals (including the ``_NODE_STATE``
    dict) into a private ``__globals__``. A bare ``_NODE_STATE[...]``
    inside such a closure therefore reads/writes a dead per-closure copy
    on the executor, while module-level helpers (pickled by reference)
    read the real module dict — a split-brain. Module *functions* are
    pickled by reference, so routing every access through this accessor
    keeps all parties on the one true dict. Resolved via ``sys.modules``
    for belt-and-braces against any by-value fallback.
    """
    import sys
    return sys.modules[__name__]._NODE_STATE


def _cleanup_ring(ring_name):
    """atexit hook: never leak a /dev/shm ring from an aborted run."""
    try:
        from tensorflowonspark_tpu import shm
        shm._load().shmring_unlink(ring_name.encode())
    except Exception:  # noqa: BLE001 - best effort at interpreter exit
        pass


class NodeContext(object):
    """Handed to the user ``map_fun`` as its second argument.

    Reference: ``TFSparkNode.py :: TFNodeContext`` — executor_id, job_name,
    task_index, cluster_spec, defaultFS, working_dir, mgr + helpers.
    """

    def __init__(self, executor_id, job_name, task_index, cluster_info,
                 cluster_meta, mgr_addr=None, mgr_authkey=None, mgr=None):
        self.executor_id = executor_id
        self.job_name = job_name
        self.task_index = task_index
        self.cluster_info = cluster_info
        self.cluster_meta = cluster_meta
        self.default_fs = cluster_meta.get("default_fs", "file://")
        self.working_dir = cluster_meta.get("working_dir", os.getcwd())
        self._mgr_addr = mgr_addr
        self._mgr_authkey = mgr_authkey
        self._mgr = mgr
        master = cluster_meta.get("master_node", "chief")
        self.num_workers = sum(
            1 for n in cluster_info
            if n.get("job_name") in (master, "chief", "worker"))

    # -- queue plane -----------------------------------------------------

    @property
    def mgr(self):
        """Queue-broker client, connected lazily (the trainer is a freshly
        spawned process and must authkey-stamp itself before connecting)."""
        if self._mgr is None:
            multiprocessing.current_process().authkey = self._mgr_authkey
            self._mgr = manager.connect(self._mgr_addr, self._mgr_authkey)
        return self._mgr

    def get_data_feed(self, train_mode=True, qname_in="input",
                      qname_out="output", input_mapping=None):
        """The queue-fed input API (reference: ``TFNodeContext.get_data_feed``)."""
        return DataFeed(self.mgr, train_mode, qname_in, qname_out, input_mapping)

    # -- paths -----------------------------------------------------------

    def absolute_path(self, path):
        """Absolutize a user path against default_fs / working dir.

        Reference: ``TFNodeContext.absolute_path`` / ``TFNode.hdfs_path``.
        The reference resolved remote schemes through TF's gfile+Hadoop;
        here remote schemes require a registered opener (fs.py) — an
        unregistered scheme fails HERE, loudly, instead of as a
        confusing ENOENT deep inside a reader.
        """
        from tensorflowonspark_tpu import fs
        if fs.scheme_of(path) is not None:
            # canonical message + chained probe cause, same as fs.open
            return fs.ensure_supported(path)
        if path.startswith("file://") or os.path.isabs(path):
            return path
        return os.path.join(self.working_dir, path)

    # -- cluster / devices ------------------------------------------------

    def cluster_spec(self):
        """{job_name: [host:port, ...]} — the TF_CONFIG-shaped view."""
        spec = {}
        for node in self.cluster_info:
            spec.setdefault(node["job_name"], []).append(
                "{}:{}".format(node["host"], node["port"]))
        return spec

    def participants(self):
        """Nodes that join the device collective: the worker family.

        ps/evaluator roles (kept for API parity, SURVEY.md §2.3) park
        outside the mesh — they never call jax.distributed and must not be
        counted as processes or host the coordinator.
        """
        return [n for n in self.cluster_info
                if n.get("job_name") not in ("ps", "evaluator")]

    def coordinator_address(self):
        """host:port of the first participant — the jax.distributed
        coordinator (its reserved port; the TF_CONFIG analog)."""
        first = self.participants()[0]
        return "{}:{}".format(first["host"], first["port"])

    def initialize_jax(self):
        """Initialize JAX for this node; the ``start_cluster_server`` analog.

        Reference: ``TFNode.start_cluster_server`` built a
        ``tf.train.Server`` from the cluster spec; here multi-host execution
        is ``jax.distributed.initialize(coordinator, N, process_id)`` over
        the worker-family participants and the collectives are
        compiler-emitted over ICI/DCN (SURVEY.md §2.4). Single-process
        clusters (and the hermetic test harness, where every trainer owns
        its own virtual device set) skip the distributed init. ps/evaluator
        nodes are not participants and get their local devices only.
        """
        participants = self.participants()
        ids = [n["executor_id"] for n in participants]
        if (len(participants) > 1 and self.executor_id in ids
                and _jax_distributed_enabled()):
            import jax

            # Cross-process collectives on the CPU backend need a host
            # transport; gloo ships with jaxlib. No-op for TPU (ICI/DCN
            # collectives are XLA-native), but it makes the CPU-device
            # harness (SURVEY.md §4's local-cluster analog) a faithful
            # multi-process rehearsal of the pod path.
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # noqa: BLE001 - older/newer jaxlib naming
                pass
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address(),
                num_processes=len(participants),
                process_id=ids.index(self.executor_id))
        import jax
        return jax.devices()

    def task_sorted_index(self):
        """This node's index in the sorted cluster_info == JAX process_id."""
        for i, node in enumerate(self.cluster_info):
            if node["executor_id"] == self.executor_id:
                return i
        raise RuntimeError(
            "executor {} not present in cluster_info".format(self.executor_id))

    def mesh(self, axis_shapes=None):
        """Build a ``jax.sharding.Mesh`` over all addressable devices.

        ``axis_shapes``: ordered {axis_name: size}; defaults to a pure
        data-parallel mesh ``{'data': n_devices}`` (the reference's only
        parallelism family, SURVEY.md §2.3). Imports jax lazily: only the
        trainer process may do this.
        """
        from tensorflowonspark_tpu.parallel import mesh as mesh_lib
        return mesh_lib.build_mesh(axis_shapes)


def _jax_distributed_enabled():
    """Default ON: a real multi-node cluster that skipped
    ``jax.distributed.initialize`` would train as N unsynchronized replicas
    and produce silently wrong models. The hermetic single-host test
    harness (where each trainer owns a private virtual CPU device set)
    opts out with ``TFOS_TPU_DISTRIBUTED=0``."""
    return os.environ.get("TFOS_TPU_DISTRIBUTED", "1") == "1"


def run(fn, tf_args, cluster_meta, tensorboard=False, log_dir=None,
        queues=("input", "output", "error"), background=True):
    """Return the bootstrap closure run once per executor.

    Reference: ``TFSparkNode.run(fn, tf_args, cluster_meta, tensorboard,
    log_dir, queues, background)`` — the returned ``_mapfn`` is shipped via
    ``nodeRDD.foreachPartitionAsync`` (SURVEY.md §3.1).
    """

    def _mapfn(iterator):
        # Partition payload is [executor_id]; also cross-check the engine's
        # persisted ordinal (reference: util.read_executor_id).
        ids = list(iterator)
        from tensorflowonspark_tpu.engine import executor as engine_executor
        info = engine_executor.get_executor_info()
        executor_id = ids[0] if ids else info.get("executor_id")

        # Duplicate-bootstrap guard (reference: cluster-id check in
        # TFSparkNode.run for retried tasks).
        if _state().get("cluster_id") == cluster_meta["id"]:
            logger.warning("executor %s already bootstrapped for cluster %s; "
                           "skipping duplicate node task", executor_id,
                           cluster_meta["id"])
            return

        job_name, task_index = _assign_role(executor_id,
                                            cluster_meta["cluster_template"])
        # Feed plane allocator tuning (8x consumer-copy rate on fresh
        # pages; util.tune_malloc docstring): set in the bootstrap
        # process so fork-started trainers inherit the tuned arena.
        util.tune_malloc()
        host = info.get("host") or util.get_ip_address()
        authkey = bytes.fromhex(cluster_meta["authkey"])
        _register_filesystems(cluster_meta)

        # 1. queue broker for this node (the process-boundary bridge)
        mgr = manager.start(authkey, list(queues),
                            mode=cluster_meta.get("manager_mode", "local"),
                            host=host)

        # 1b. native shm ring: the feed fast path when the broker is
        # local (feeder and trainer share this host — always true for
        # the fork/spawn trainer below). The default is 'auto': a
        # measured-at-startup micro-probe picks whichever transport
        # actually moves a representative chunk faster ON THIS HOST
        # (the two are within noise on small boxes, and a wrong static
        # default costs the whole feed plane). TFOS_FEED_TRANSPORT=
        # shm|queue forces; remote-mode brokers stay on queues (the
        # ring is host-local).
        ring = None
        transport = os.environ.get("TFOS_FEED_TRANSPORT")
        if transport is None:
            transport = ("auto" if cluster_meta.get("manager_mode", "local")
                         == "local" else "queue")
        if transport in ("shm", "auto"):
            from tensorflowonspark_tpu import shm
            probe_rates = None
            if shm.available():
                # the creator pid in the name is what lets sweep_stale
                # prove a segment's owner died (SIGKILL leaves no other
                # cleanup path); the sweep clears THIS slot's leftovers
                # from any earlier cluster before we allocate
                shm.sweep_stale(executor_id)
                ring_name = "/tfos-{}-{}.{}".format(
                    cluster_meta["id"][-10:], executor_id, os.getpid())
                shm._load().shmring_unlink(ring_name.encode())  # clear stale
                try:
                    ring = shm.ShmRing.create(ring_name)
                except OSError as e:
                    probe_rates = {"error": "ring create failed: %s" % e}
                    logger.warning("shm ring disabled (%s); using queues", e)
                if ring is not None and transport == "auto":
                    choice, probe_rates = _probe_feed_transport(ring)
                    # the probe moved real bytes through the ring, and a
                    # failed leg may leave a consumer thread behind:
                    # recreate the segment either way so the trainer can
                    # never read probe residue as training data (the
                    # zombie's mmap stays valid but orphaned)
                    ring.close()
                    shm._load().shmring_unlink(ring_name.encode())
                    ring = None
                    if choice == "shm":
                        try:
                            ring = shm.ShmRing.create(ring_name)
                        except OSError as e:
                            probe_rates = dict(
                                probe_rates,
                                error="ring recreate failed: %s" % e)
                            logger.warning("shm ring recreate failed (%s); "
                                           "using queues", e)
                    else:
                        logger.info("transport probe picked queue (%s)",
                                    probe_rates)
                if ring is not None:
                    mgr.set("shm_name", ring_name)
                    import atexit
                    atexit.register(_cleanup_ring, ring_name)
                    logger.info("feed fast path: shm ring %s", ring_name)
            else:
                probe_rates = {"error": "native shm ring unavailable"}
                log = (logger.warning if transport == "shm" else logger.info)
                log("shm feed transport %s but the native ring is "
                    "unavailable; using queues",
                    "requested" if transport == "shm" else "probed")
            if transport == "auto":
                # every auto run records why its transport was chosen
                mgr.set("feed_transport_probe", probe_rates)
        # the effective transport, observable by feeders/tools either way
        mgr.set("feed_transport", "shm" if ring is not None else "queue")

        # 2. reserve the port this node serves on (chief's doubles as the
        # jax.distributed coordinator address)
        port = int(os.environ.get("TFOS_SERVER_PORT", 0)) or util.find_free_port()

        # 3. optional tensorboard on the designated master node
        tb_port, tb_pid = 0, 0
        if tensorboard and job_name == cluster_meta.get("master_node", "chief"):
            tb_port, tb_pid = _start_tensorboard(log_dir)

        # 4. register with the driver's barrier; block until cluster formed
        client = reservation.Client(cluster_meta["server_addr"])
        node_meta = {"executor_id": executor_id, "host": host,
                     "job_name": job_name, "task_index": task_index,
                     "port": port, "tb_port": tb_port, "tb_pid": tb_pid,
                     "mgr_addr": list(mgr.address), "pid": os.getpid()}
        client.register(node_meta)
        cluster_info = client.await_reservations(
            timeout=cluster_meta.get("reservation_timeout",
                                     reservation.DEFAULT_TIMEOUT))
        client.close()
        logger.info("node %s/%d (executor %s) sees cluster of %d",
                    job_name, task_index, executor_id, len(cluster_info))

        mgr.set("endpoint", {"host": host, "mgr_addr": list(mgr.address)})

        ctx = NodeContext(executor_id, job_name, task_index, cluster_info,
                          cluster_meta, mgr_addr=mgr.address,
                          mgr_authkey=authkey, mgr=mgr)

        _state().update(cluster_id=cluster_meta["id"], mgr=mgr,
                        executor_id=executor_id, ctx=ctx,
                        trainer_proc=None, tb_pid=tb_pid, shm_ring=ring)

        # Supervision heartbeat lease (supervisor.py): a small status
        # beat to the driver's reservation server, carrying the three
        # liveness signals the Supervisor classifies — node state +
        # feed progress (broker kv), trainer process exit status, and
        # the beat's very arrival (executor liveness). Always on: the
        # beat is one tiny JSON message per interval and the lease
        # table is what makes an unsupervised cluster debuggable too.
        # Seed the metrics kv with an empty registry snapshot BEFORE
        # the first beat: the driver's rollup then distinguishes "node
        # up, feed idle" (empty snapshot) from "no observability plane"
        # (None) even while the trainer process is still importing —
        # the trainer's DataFeed overwrites it with real numbers.
        from tensorflowonspark_tpu import tracing as tracing_mod
        mgr.set("metrics", tracing_mod.MetricsRegistry().snapshot())
        _start_beat_thread(cluster_meta, mgr, executor_id)

        if background:
            # InputMode.SPARK: the trainer runs in a child process (it will
            # own the TPU); this bootstrap task returns so the executor's
            # task slot frees up for feed tasks (SURVEY.md §3.2).
            # Start method: fork (default) is safe *because this executor
            # process never initializes jax/libtpu* — the child is the first
            # TPU toucher — and it inherits the user fn without pickling.
            # spawn (TFOS_TRAINER_START_METHOD=spawn) is available for
            # paranoid isolation; it ships one opaque cloudpickle payload,
            # since mp re-pickles spawn args with *standard* pickle, which
            # cannot handle dynamically-defined closures.
            method = os.environ.get("TFOS_TRAINER_START_METHOD", "fork")
            if method == "fork":
                proc = multiprocessing.get_context("fork").Process(
                    target=_trainer_main_fork,
                    args=(fn, tf_args, executor_id, job_name, task_index,
                          cluster_info, cluster_meta, list(mgr.address)),
                    name="tfos-trainer-%s" % executor_id)
            else:
                from tensorflowonspark_tpu.engine import serializer
                payload = serializer.dumps(
                    (fn, tf_args, executor_id, job_name, task_index,
                     cluster_info, cluster_meta, list(mgr.address)))
                proc = multiprocessing.get_context("spawn").Process(
                    target=_trainer_main, args=(payload,),
                    name="tfos-trainer-%s" % executor_id)
            proc.daemon = True
            proc.start()
            _state()["trainer_proc"] = proc
            logger.info("spawned background trainer pid %d", proc.pid)

            # Watchdog: a trainer killed without running its exception
            # handler (OOM SIGKILL) would leave state='running' and feeders
            # blocked until feed_timeout; flip state the moment it exits
            # abnormally. (Reference has no analog — its feeders just time
            # out; SURVEY.md §5 failure-detection.)
            def _watch(proc=proc, mgr=mgr, executor_id=executor_id):
                proc.join()
                try:
                    # surfaced to the supervisor via the heartbeat lease
                    # payload AND readable from user/test code
                    mgr.set("trainer_exit", proc.exitcode)
                except Exception:  # noqa: BLE001 - broker may be gone
                    pass
                if proc.exitcode not in (0, None) and \
                        mgr.get("state") == "running":
                    msg = ("trainer on executor {} exited with code {} "
                           "without reporting an error (killed?)".format(
                               executor_id, proc.exitcode))
                    logger.error(msg)
                    try:
                        mgr.get_queue("error").put(msg)
                        mgr.set("state", "error")
                    except Exception:
                        pass

            # tfos: unjoined(exits with the trainer process it watches; the executor task has no later teardown hook)
            threading.Thread(target=_watch, name="trainer-watchdog",
                             daemon=True).start()
        else:
            # InputMode.TENSORFLOW: run inline; exceptions go to the error
            # queue AND re-raise to fail the task (driver sees both).
            try:
                fn(tf_args, ctx)
            except BaseException as e:  # noqa: BLE001
                import traceback
                tb = traceback.format_exc()
                logger.error("user map_fun failed:\n%s", tb)
                mgr.get_queue("error").put(tb)
                raise

    return _mapfn


#: executor-hosted serving nodes in THIS process, keyed by replica_id
#: (fleet.ServingNode objects). Module-level for the same reason as
#: _NODE_STATE: the serve/stop closures ship by value, so access goes
#: through a module function that both sides resolve via sys.modules.
_SERVING_STATE = {}


def _serving_state():
    import sys
    return sys.modules[__name__]._SERVING_STATE


def serve_replica(spec):
    """Return the ``role: "serving"`` bootstrap closure, run once on
    the target executor (PR 13): the paper's executor-role map_fun
    applied to the serving plane. The closure builds the replica
    IN the executor process — ``fleet.ServingNode``: DecodeEngine
    (spawn config rides ``spec["engine_kw"]`` — slots, paging,
    ``attn_impl``; the multi-tenant QoS policy — tenant weights,
    priority classes, token quotas — rides ``spec["qos"]``, applied as
    the engine's ``qos_policy`` so every executor-hosted replica
    enforces the same tenant contract the router does, PR 18),
    ModelServer on an ephemeral port with the remote
    lifecycle RPCs mounted, and the BEAT agent registering the
    replica's real HTTP address with the driver's reservation server —
    then RETURNS, leaving the node serving on daemon threads (the
    executor's task slot frees; the driver reaches the node over HTTP
    from here on). Unlike the training bootstrap, the engine runs in
    the executor process itself: a serving executor IS its accelerator
    owner, there is no feed plane to keep jax out of.

    A task retried onto an executor already hosting this replica_id
    stops the incumbent first (the re-spawn semantics the autoscaler's
    replacement path relies on when a revived executor is chosen
    again)."""

    def _mapfn(iterator):
        for _ in iterator:
            pass
        from tensorflowonspark_tpu import fleet as fleet_mod
        from tensorflowonspark_tpu.engine import executor as engine_executor

        info = engine_executor.get_executor_info()
        executor_id = info.get("executor_id")
        if executor_id is None:
            executor_id = util.read_executor_id()
        rid = str(spec["replica_id"])
        # chaos gate: kill_serving_executor_at_request refuses to fire
        # in any process that is not an executor-hosted serving node
        os.environ["TFOS_SERVING_EXECUTOR_ID"] = str(executor_id)
        # reap KV-ship rings a SIGKILLed predecessor left in /dev/shm
        # (PR 17): ship-ring names embed the creator pid exactly like
        # the feed rings, so the stale sweep can prove owner death
        # before this node's prefill side allocates fresh ones; scoped
        # to the kvship family so a co-hosted training cluster's feed
        # rings are never touched from the serving bootstrap
        try:
            from tensorflowonspark_tpu import shm
            if shm.available():
                swept = shm.sweep_stale(
                    pattern="/dev/shm/tfos-kvship-*.*")
                if swept:
                    logger.warning("reaped %d stale kv-ship ring(s): "
                                   "%s", len(swept), swept)
        except Exception:  # noqa: BLE001 - bootstrap must not die on it
            logger.exception("kv-ship ring sweep failed")
        old = _serving_state().pop(rid, None)
        if old is not None:
            logger.warning("executor %s already hosts replica %s; "
                           "stopping the incumbent before re-spawning",
                           executor_id, rid)
            try:
                old.stop()
            except Exception:  # noqa: BLE001 - replaced either way
                logger.exception("incumbent replica %s stop failed", rid)
        host = info.get("host") or util.get_ip_address()
        node = fleet_mod.ServingNode(spec, executor_id=executor_id,
                                     host=host)
        node.start()
        _serving_state()[rid] = node

    return _mapfn


def stop_replica(replica_id):
    """Closure that stops an executor-hosted replica in place (the
    task-based fallback when the /admin/stop RPC cannot be used)."""

    def _mapfn(iterator):
        for _ in iterator:
            pass
        node = _serving_state().pop(str(replica_id), None)
        if node is not None:
            node.stop()

    return _mapfn


#: default seconds between heartbeat-lease beats (env: TFOS_BEAT_INTERVAL;
#: supervised runs tighten it via SupervisorConfig -> cluster_meta)
DEFAULT_BEAT_INTERVAL = 2.0


def _beat_payload(mgr, executor_id):
    """One heartbeat lease payload: the supervisor's raw signal set."""
    proc = _state().get("trainer_proc")

    def _kv(key):
        try:
            return mgr.get(key)
        except Exception:  # noqa: BLE001 - broker may be gone at teardown
            return None

    return {"state": _kv("state"), "feed_hb": _kv("feed_hb"),
            "train_step": _kv("train_step"),
            "restored_step": _kv("restored_step"),
            "feed_transport": _kv("feed_transport"),
            # compact MetricsRegistry snapshot the trainer's DataFeed
            # publishes alongside feed_hb (tracing.py PR 5): the lease
            # carries each executor's feed-stage breakdown to the
            # driver, where cluster.metrics() merges the fleet's view
            # and a failure's incident evidence quotes the stalled
            # executor's stages
            "metrics": _kv("metrics"),
            "trainer_alive": None if proc is None else proc.is_alive(),
            "trainer_exit": None if proc is None else proc.exitcode,
            "executor_id": executor_id, "pid": os.getpid()}


def _start_beat_thread(cluster_meta, mgr, executor_id):
    """Publish this node's heartbeat lease to the reservation server.

    Daemon thread; exits when this node's cluster incarnation ends
    (shutdown pops the state's cluster_id; a reform replaces it) or the
    node reaches the stopped state. A dead/unreachable server just drops
    the connection and retries next tick — beats must never take a node
    down. chaos.on_heartbeat() gates each send so the harness can
    simulate an executor going dark without killing anything.
    """
    interval = float(os.environ.get("TFOS_BEAT_INTERVAL", 0) or
                     cluster_meta.get("beat_interval") or
                     DEFAULT_BEAT_INTERVAL)
    cluster_id = cluster_meta["id"]
    server_addr = cluster_meta["server_addr"]

    def _beat_loop():
        from tensorflowonspark_tpu import chaos
        client = None
        payload = None
        try:
            while _state().get("cluster_id") == cluster_id:
                payload = _beat_payload(mgr, executor_id)
                if not chaos.on_heartbeat():
                    try:
                        if client is None:
                            # short connect bound (PR 19): a dead
                            # reservation server must cost one tick a
                            # few seconds, not the OS connect timeout
                            client = reservation.Client(
                                server_addr, connect_timeout=5)
                        client.beat(executor_id, payload)
                    except Exception:  # noqa: BLE001 - beat must retry
                        # ANY send failure (conn refused, EOF mid-reply,
                        # codec error) drops the connection and retries
                        # next tick — a beat thread that dies silently
                        # blinds the supervisor to every later failure
                        logger.debug("heartbeat send failed; will retry",
                                     exc_info=True)
                        if client is not None:
                            try:
                                client.close()
                            except Exception:  # noqa: BLE001
                                pass
                        client = None
                if payload.get("state") == "stopped":
                    break
                time.sleep(interval)
            logger.info("beat loop for executor %s exiting: cluster_id=%r "
                        "(beating %r), state=%r", executor_id,
                        _state().get("cluster_id"), cluster_id,
                        payload.get("state") if payload else None)
        except BaseException:
            logger.exception("beat loop for executor %s died", executor_id)
            raise
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

    # tfos: unjoined(silenced by _shutdown's final SYNCHRONOUS beat at teardown; the daemon loop ends with the executor)
    threading.Thread(target=_beat_loop, name="tfos-beat-%s" % executor_id,
                     daemon=True).start()


def _register_filesystems(cluster_meta):
    """Replay driver-provided {scheme: opener} registrations here.

    The fs registry is process-local (fs.py); cluster.run ships the
    openers in cluster_meta so executors, trainers, and data-task
    processes all resolve the same remote schemes. Idempotent.
    """
    openers = cluster_meta.get("filesystems") or {}
    if openers:
        from tensorflowonspark_tpu import fs
        for scheme, opener in openers.items():
            fs.register_filesystem(scheme, opener)


def _trainer_main(payload):
    """spawn-mode entry: unwrap the cloudpickle payload first."""
    from tensorflowonspark_tpu.engine import serializer
    util.tune_malloc()  # spawn starts a fresh libc: re-apply the tuning
    _trainer_main_fork(*serializer.loads(payload))


def _close_inherited_sockets():
    """Close every socket fd a forked trainer inherited from the executor.

    Fork duplicates the executor's fds — including its engine-driver
    connection and the queue broker's *listen* socket — and those
    duplicates break failure detection from the grave (found by the
    chaos suite, VERDICT r4 task 7): when the executor is SIGKILLed,
    (a) the driver never sees EOF on its executor connection because
    the trainer's copy keeps the TCP stream established, so the engine
    hangs instead of failing the task; and (b) the trainer's own broker
    reconnect SUCCEEDS against the inherited listen socket that nothing
    accepts on, parking the error path in recv() forever. The trainer
    needs none of these — it builds every connection it uses fresh
    (broker by address, ring by name) — so owning zero inherited
    sockets restores the invariant that a process's death closes its
    endpoints.

    dup2(/dev/null) rather than close(): the forked copies of the
    executor's python socket objects still reference these fd numbers,
    and a bare close would free the numbers for reuse — a stale
    object's destructor could then close an unrelated fd the trainer
    opened later. dup2 drops the kernel socket reference (what we
    need) while keeping the slot occupied by /dev/null, which the
    stale destructors may close harmlessly.
    """
    import stat as stat_mod
    fds = None
    for fd_dir in ("/proc/self/fd", "/dev/fd"):  # linux, then macOS/BSD
        try:
            fds = [int(f) for f in os.listdir(fd_dir)]
            break
        except OSError:
            continue
    if fds is None:  # no fd listing on this platform: nothing safe to do
        return
    devnull = os.open(os.devnull, os.O_RDWR)
    for fd in fds:
        if fd < 3 or fd == devnull:
            continue
        try:
            if stat_mod.S_ISSOCK(os.fstat(fd).st_mode):
                os.dup2(devnull, fd)
        except OSError:
            continue
    os.close(devnull)


def _trainer_main_fork(fn, tf_args, executor_id, job_name, task_index,
                       cluster_info, cluster_meta, mgr_addr):
    """Entry of the trainer process — the TPU owner.

    Mirrors the reference's ``fn_wrapper``: run the user fn; on exception,
    push the traceback to the 'error' queue so ``shutdown()`` can re-raise
    it on the driver (SURVEY.md §3.5).
    """
    _close_inherited_sockets()
    logging.basicConfig(
        level=os.environ.get("TFOS_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s trainer[{}] %(name)s: %(message)s"
        .format(executor_id))
    # chaos.py scoping: `only=EID` injections fire in the one trainer
    # whose executor matches (how a blacklist test kills one node of N)
    os.environ["TFOS_TRAINER_EXECUTOR_ID"] = str(executor_id)
    authkey = bytes.fromhex(cluster_meta["authkey"])
    multiprocessing.current_process().authkey = authkey
    _register_filesystems(cluster_meta)  # spawn mode starts from scratch
    ctx = NodeContext(executor_id, job_name, task_index, cluster_info,
                      cluster_meta, mgr_addr=tuple(mgr_addr),
                      mgr_authkey=authkey)
    try:
        fn(tf_args, ctx)
    except BaseException:  # noqa: BLE001 - must reach the driver
        import traceback
        tb = traceback.format_exc()
        logger.error("trainer failed:\n%s", tb)
        try:
            ctx.mgr.get_queue("error").put(tb)
            ctx.mgr.set("state", "error")
        except Exception:
            pass
        sys.exit(1)


def _assign_role(executor_id, cluster_template):
    """executor ordinal -> (job_name, task_index).

    Reference: the cluster_template built in ``TFCluster.run`` maps executor
    index ranges to ps/chief/worker/evaluator roles.
    """
    for job_name, ids in cluster_template.items():
        if executor_id in ids:
            return job_name, ids.index(executor_id)
    raise RuntimeError(
        "executor {} not in cluster template {}".format(
            executor_id, cluster_template))


def _start_tensorboard(log_dir):
    """Spawn `tensorboard --logdir` if the binary exists; (port, pid)."""
    import shutil
    exe = shutil.which("tensorboard")
    if exe is None or not log_dir:
        logger.info("tensorboard unavailable or no log_dir; skipping")
        return 0, 0
    port = util.find_free_port()
    proc = subprocess.Popen(
        [exe, "--logdir", log_dir, "--port", str(port), "--bind_all"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    logger.info("tensorboard pid %d on port %d", proc.pid, port)
    return port, proc.pid


# -- data-plane closures (run on arbitrary executors) ----------------------

def _get_manager(cluster_info, cluster_meta, executor_id):
    """Connect to the queue broker of the node on this executor.

    Reference: ``TFSparkNode._get_manager``. Fast path: the broker lives in
    this very process (our engine runs feed tasks in the executor process
    that bootstrapped the node) — use the cached client. Slow path: look up
    the node's advertised mgr_addr in cluster_info and connect with the
    cluster authkey from cluster_meta.
    """
    st = _state()
    if st.get("executor_id") == executor_id and "mgr" in st:
        return st["mgr"]
    for node in cluster_info:
        if node["executor_id"] == executor_id:
            authkey = bytes.fromhex(cluster_meta["authkey"])
            multiprocessing.current_process().authkey = authkey
            return manager.connect(tuple(node["mgr_addr"]), authkey)
    raise RuntimeError(
        "no cluster node found for executor {}".format(executor_id))


def _local_executor_id():
    from tensorflowonspark_tpu.engine import executor as engine_executor
    info = engine_executor.get_executor_info()
    eid = info.get("executor_id")
    if eid is None:
        eid = util.read_executor_id()
    return eid


def train(cluster_info, cluster_meta, feed_timeout=600, qname="input"):
    """Feed closure: push this partition's records into the local node's
    input queue, chunked; block until consumed.

    Reference: ``TFSparkNode.train`` → ``_train`` (SURVEY.md §3.2 hot path).
    """

    def _train(iterator):
        _feed_one_partition(iterator, cluster_info, cluster_meta,
                            feed_timeout, qname)

    return _train


def _feed_one_partition(iterator, cluster_info, cluster_meta, feed_timeout,
                        qname="input"):
    """Feed one partition into this executor's node; True iff the node
    consumed it fully (the feed-level acknowledgement supervisor.py's
    replay bookkeeping is built on). Shared by the plain ``train``
    closure and the supervised acked-feed closure."""
    mgr = _get_manager(cluster_info, cluster_meta, _local_executor_id())
    state = mgr.get("state")
    if state in ("terminating", "stopped", "error"):
        logger.info("feed task skipping: node state is %r", state)
        # Drain the partition so upstream iterators don't block.
        for _ in iterator:
            pass
        return False
    count = _feed_partition(iterator, mgr, qname, feed_timeout)
    # block (bounded) until the partition is consumed
    consumed = _join_feed(mgr, qname, feed_timeout)
    logger.info("fed %d records to %r (consumed=%s)", count, qname, consumed)
    return bool(consumed)


def _feed_ring(qname):
    """The node's shm ring, when the fast path is active for this queue."""
    if qname == "input":
        return _state().get("shm_ring")
    return None


def _columnar_leaves(record):
    """``record``'s field values iff the feed would columnarize it;
    None otherwise. THE one gate shared by _pack_chunk (whether to
    stack) and _chunk_limit (whether byte-targeted sizing applies) —
    a drifted copy would size chunks for a packing that never happens.

    Only records whose fields are numpy numeric values (arrays or 0-d
    scalars — a ``(image, np.int64_label)`` tuple is the canonical feed
    record and must not flunk this gate) qualify: python scalars /
    strings / objects must round-trip with their exact types, and only
    bulk array payloads benefit from raw-byte framing anyway.
    """
    import numpy as np

    if isinstance(record, dict):
        leaves = list(record.values())
    elif isinstance(record, (tuple, list)):
        leaves = list(record)
    else:
        leaves = [record]
    if leaves and all(
            isinstance(v, (np.ndarray, np.generic))
            and v.dtype.kind in "biufc"
            for v in leaves):
        return leaves
    return None


def _pack_chunk(records):
    """Stack a chunk of records into a ColumnarChunk when possible.

    Columnar chunks move as raw contiguous bytes (frames.py) and the
    consumer re-slices them without per-record work — the feed plane's
    main copy-count lever (SURVEY.md §7.3). Records that don't stack
    (ragged shapes, object/string payloads) fall back to the plain list
    chunk with identical semantics.
    """
    from tensorflowonspark_tpu import frames as frames_lib

    if _columnar_leaves(records[0]) is None:
        return list(records)
    try:
        return frames_lib.ColumnarChunk.from_records(records)
    except Exception:  # noqa: BLE001 - ragged shapes etc → legacy path
        return list(records)


def _pack_chunks(records):
    """``records`` → list of feed items to enqueue.

    Normally one item. The exception: a size-targeted accumulation
    (``_chunk_limit``, up to FEED_CHUNK_MAX records, sized from the
    FIRST record) whose later records turned out ragged/mixed falls
    back to a pickled row list — unsplittable by the ring's oversize
    path and a single giant pickle on the queue — so oversized fallback
    lists re-split to the legacy FEED_CHUNK bound here.
    """
    packed = _pack_chunk(records)
    if isinstance(packed, list) and len(packed) > FEED_CHUNK:
        return [packed[i:i + FEED_CHUNK]
                for i in range(0, len(packed), FEED_CHUNK)]
    return [packed]


def _chunk_limit(first_record):
    """Records per chunk for this partition: size-targeted for records
    the feed will columnarize (same gate as _pack_chunk — byte-sizing a
    pickled-row chunk would 16x a path the frame target was never meant
    to touch), FEED_CHUNK otherwise.

    Never sized BELOW FEED_CHUNK — bulk-regime records (147KB images)
    already hit multi-MB frames at 256 records and shrinking them would
    regress the tuned path; the target only coalesces MORE records when
    they are small.
    """
    leaves = _columnar_leaves(first_record)
    if leaves is None:
        return FEED_CHUNK
    rec_bytes = sum(v.nbytes for v in leaves) or 1
    try:
        target = int(os.environ.get("TFOS_FEED_FRAME_BYTES", "") or
                     FEED_FRAME_BYTES)
    except ValueError:
        target = FEED_FRAME_BYTES
    return max(FEED_CHUNK, min(FEED_CHUNK_MAX, target // rec_bytes))


def _feed_partition(iterator, mgr, qname, feed_timeout, cancel=None):
    """Push one partition into ``qname`` as chunks + EndPartition; returns
    the record count. Shared by the train and inference feed closures.
    Transport is the shm ring when active (node bootstrap created it),
    else the manager queue. ``cancel`` (a ``threading.Event``) aborts the
    feed between chunks — set by a concurrent consumer that failed, so a
    background feeder never outlives its task.

    Two per-message-cost amortizations for the small-batch regime:
    chunks are size-targeted (``_chunk_limit`` — tiny records pack into
    ~FEED_FRAME_BYTES frames instead of 256-record slivers), and on the
    ring the partition's final chunk coalesces with its EndPartition
    marker into ONE gather write (``frames.FrameList``) — for a
    small partition that halves the message count outright. One chunk is
    buffered (``prev``) to make the tail identifiable; backpressure
    semantics are unchanged, the feeder just runs one chunk ahead.
    """
    ring = _feed_ring(qname)
    q = None if ring is not None else mgr.get_queue(qname)

    def put(obj, deadline):
        if cancel is not None and cancel.is_set():
            raise RuntimeError("feed cancelled by consumer")
        if ring is not None:
            _ring_put(ring, obj, mgr, deadline, cancel=cancel)
        else:
            _bounded_put(q, obj, mgr, deadline, cancel=cancel)

    deadline = time.monotonic() + feed_timeout
    chunk = []
    limit = None
    prev = None
    count = 0

    def emit(obj):
        """Buffer one item; flush the previously buffered one."""
        nonlocal prev, deadline
        if prev is not None:
            put(prev, deadline)
            deadline = time.monotonic() + feed_timeout
        prev = obj

    for item in iterator:
        if limit is None:
            limit = _chunk_limit(item)
        chunk.append(item)
        if len(chunk) >= limit:
            for packed in _pack_chunks(chunk):
                emit(packed)
            count += len(chunk)
            chunk = []
    if chunk:
        for packed in _pack_chunks(chunk):
            emit(packed)
        count += len(chunk)
    end = marker.EndPartition()
    if prev is None:
        put(end, deadline)
    else:
        # Both transports coalesce the final chunk with its EndPartition
        # into ONE message. On the ring that halves the tail's message
        # count; on the queue it additionally makes the partition ack
        # prompt: the consumer unpacks the marker in the same next_batch
        # call that returns the final chunk, so ``queue.join()`` — and a
        # supervised feed's ACK — completes with the batch, not one call
        # later (the off-by-one that would make a kill-after-step-N
        # replay an already-consumed partition).
        from tensorflowonspark_tpu import frames as frames_lib
        put(frames_lib.FrameList([prev, end]), deadline)
    return count


def _probe_feed_transport(ring, reps=4, records=32):
    """Measured-at-startup transport pick; returns ('shm'|'queue', rates).

    VERDICT r4 weak #1: a static shm-when-local default had the one
    driver-captured smoke showing the ring *losing* to the queue. This
    pushes the same representative columnar chunk through both
    transports' dominant cost paths — the queue leg as pickle + TCP
    loopback round trips (what the manager-proxy hop pays per chunk;
    see the in-function note for why not real proxies), the shm leg
    through write_obj/read_obj on the live ring — and picks the
    measured winner. Ties break toward shm: equal copy cost still
    leaves the manager socket free for control traffic. Any probe
    failure keeps shm (the pre-probe default) so a broken probe can
    never disable the fast path.

    The probe moves real bytes through ``ring``, and a failed leg can
    leave its consumer thread (and unread residue) behind — the caller
    must recreate the ring segment afterwards, never feed through the
    probed one.
    """
    import numpy as np

    from tensorflowonspark_tpu import frames as frames_lib

    chunk = frames_lib.ColumnarChunk(
        [np.zeros((records, 64, 64, 3), np.float32),
         np.zeros((records,), np.int32)], names=("x", "y"))
    nbytes = sum(c.nbytes for c in chunk.cols)

    def timed(write_one, read_one):
        errs = []

        def consume():
            try:
                for _ in range(reps):
                    read_one()
            except Exception as e:  # noqa: BLE001 - surfaces as no-pick
                errs.append(e)

        t = threading.Thread(target=consume, daemon=True,
                             name="transport-probe-consumer")
        t0 = time.monotonic()
        t.start()
        for _ in range(reps):
            write_one()
        t.join(timeout=30)
        if t.is_alive() or errs:
            raise RuntimeError("probe leg failed: {}".format(
                errs[0] if errs else "consumer timeout"))
        return time.monotonic() - t0

    listener = None
    try:
        def shm_read():
            if ring.read_obj(timeout=10.0) is None:
                raise TimeoutError("ring read timed out")

        t_shm = timed(lambda: ring.write_obj(chunk, timeout=10.0), shm_read)

        # Queue leg: a raw TCP Connection pair over loopback — the same
        # pickle + TCP wire cost the manager-proxy path pays per chunk,
        # WITHOUT touching the live broker. Deliberately not manager
        # proxies: a BaseProxy plants an mp Finalize whose _decref does
        # blocking connect+challenge I/O at GC/exit time against this
        # process's own single-accepter server — under feed load that
        # wedged the accepter mid-Thread.start() and starved the
        # trainer's handshake (found via the deep-partition test).
        # A fresh authkey keeps the HMAC challenge on the pair (an
        # unauthenticated listener would unpickle whatever local peer
        # connected first), and SO_SNDTIMEO bounds the writes so a dead
        # consumer can't wedge bootstrap in send().
        import socket as _socket
        import struct as _struct
        from multiprocessing.connection import Client as _ConnClient
        from multiprocessing.connection import Listener as _Listener

        probe_key = os.urandom(16)
        listener = _Listener(("127.0.0.1", 0), authkey=probe_key)
        rconn_box = {}

        def _accept():
            rconn_box["c"] = listener.accept()

        # the authkey handshake is synchronous on BOTH ends, so accept
        # must already be in flight when Client() connects
        acceptor = threading.Thread(target=_accept, daemon=True,
                                    name="tfos-probe-accept")
        acceptor.start()
        wconn = _ConnClient(listener.address, authkey=probe_key)
        try:  # from here every exit path must close both pair ends
            acceptor.join(timeout=10)
            if "c" not in rconn_box:
                raise RuntimeError("probe pair handshake timed out")
            _socket.socket(fileno=os.dup(wconn.fileno())).setsockopt(
                _socket.SOL_SOCKET, _socket.SO_SNDTIMEO,
                _struct.pack("ll", 10, 0))

            def q_read():
                rconn_box["c"].recv()

            def q_write():
                wconn.send(chunk)

            t_queue = timed(q_write, q_read)
        finally:
            wconn.close()
            if "c" in rconn_box:
                rconn_box["c"].close()
    except Exception as e:  # noqa: BLE001 - probe is advisory
        logger.warning("transport probe failed (%s); keeping shm", e)
        return "shm", {"error": str(e)}
    finally:
        if listener is not None:
            try:
                listener.close()
            except Exception:  # noqa: BLE001
                pass

    rate = lambda t: round(reps * nbytes / t / 1e6, 1) if t > 0 else float("inf")  # noqa: E731,E501
    rates = {"shm_mb_s": rate(t_shm), "queue_mb_s": rate(t_queue)}
    choice = "shm" if t_shm <= 1.1 * t_queue else "queue"
    logger.info("feed transport probe: %s -> %s", rates, choice)
    return choice, rates


#: serializes same-process ring writers: the ring is SPSC, and an engine
#: that ever runs two feed tasks concurrently in one executor process
#: must not interleave gather-writes (the queue transport was implicitly
#: thread-safe; this keeps the ring equally safe).
_RING_WRITE_LOCK = threading.Lock()


def _ring_put(ring, obj, mgr, deadline, cancel=None):
    """shm-ring analog of _bounded_put: bounded writes + state checks.

    Frame-encodes once; retries move no bytes until space frees. A
    ``frames.FrameList`` coalesces several objects into one message
    (gather write — the tail-coalescing path). A frame too large for the
    ring (> capacity/2) de-coalesces first, then splits chunks
    record-wise and re-sends — semantics are unchanged since DataFeed
    re-slices chunks anyway."""
    from tensorflowonspark_tpu import frames as frames_lib

    multi = isinstance(obj, frames_lib.FrameList)
    bufs = frames_lib.encode_multi(obj) if multi else frames_lib.encode(obj)
    while True:
        try:
            with _RING_WRITE_LOCK:
                ring.write_buffers(bufs, timeout=1.0)
            return
        except TimeoutError:
            if cancel is not None and cancel.is_set():
                raise RuntimeError("feed cancelled by consumer")
            if mgr.get("state") in ("terminating", "stopped", "error"):
                raise RuntimeError("feed aborted: node is terminating")
            if time.monotonic() > deadline:
                raise RuntimeError("feed timeout exceeded")
        except ValueError:
            if multi:
                for part in obj:
                    _ring_put(ring, part, mgr, deadline, cancel=cancel)
                return
            if isinstance(obj, frames_lib.ColumnarChunk) and len(obj) > 1:
                half = len(obj) // 2
                _ring_put(ring, obj.slice(0, half), mgr, deadline,
                          cancel=cancel)
                _ring_put(ring, obj.slice(half, len(obj)), mgr, deadline,
                          cancel=cancel)
                return
            raise RuntimeError(
                "feed record does not fit the shm ring; raise "
                "TFOS_SHM_CAPACITY or lower FEED_CHUNK")


def _join_feed(mgr, qname, feed_timeout, on_error="return"):
    """Wait (bounded) for the queue to drain; never hang on a dead trainer.

    The reference's feeder does a bare ``queue.join()`` — correct while the
    trainer lives, a permanent hang when it died mid-batch. Here the join is
    chunked with state checks: trainer error/termination either returns
    (train path — the real traceback surfaces at ``shutdown()``) or raises
    (inference path — results can never arrive); feed_timeout still raises.
    """
    ring = _feed_ring(qname)

    def _drained():
        if ring is not None:
            return ring.wait_drained(timeout=1.0)
        return mgr.join_queue(qname, 1.0)

    deadline = time.monotonic() + feed_timeout
    while not _drained():
        state = mgr.get("state")
        if state in ("error", "terminating", "stopped"):
            if on_error == "raise":
                raise RuntimeError(
                    "feed incomplete: node state is {!r}".format(state))
            logger.warning("feed incomplete: node state is %r", state)
            return False
        if time.monotonic() > deadline:
            raise RuntimeError("feed timeout: partition not consumed within "
                               "{}s".format(feed_timeout))
    return True


def _put_chunk(q, chunk, mgr, deadline):
    _bounded_put(q, list(chunk), mgr, deadline)


def _bounded_put(q, item, mgr, deadline, cancel=None):
    """put with terminating-state + timeout checks (reference: abort if
    mgr state == 'terminating'; raise on feed_timeout -> task fail).
    The broker queues are bounded (manager.QUEUE_MAXSIZE), so queue.Full
    is the live backpressure path.

    Only ``queue.Full`` is retried — anything else (e.g. an unpicklable
    record) must surface immediately with its real traceback, not spin
    until a misleading 'feed timeout'.
    """
    while True:
        try:
            q.put(item, block=True, timeout=1.0)
            return
        except _queue.Full:
            if cancel is not None and cancel.is_set():
                raise RuntimeError("feed cancelled by consumer")
            if mgr.get("state") in ("terminating", "stopped", "error"):
                raise RuntimeError("feed aborted: node is terminating")
            if time.monotonic() > deadline:
                raise RuntimeError("feed timeout exceeded")


def inference(cluster_info, cluster_meta, feed_timeout=600, qname="output"):
    """Inference closure: push partition records, then pull exactly as many
    results as records pushed; yields result rows.

    Reference: ``TFSparkNode.inference`` → ``_inference`` (SURVEY.md §3.3):
    per-partition count/order is guaranteed by ``q_in.join()`` + counted
    ``q_out`` reads.
    """

    def _inference(iterator):
        mgr = _get_manager(cluster_info, cluster_meta, _local_executor_id())

        # Feed in a background thread and drain results HERE, concurrently:
        # feeding the whole partition before touching the output queue
        # (the reference's order) wedges once BOTH bounded queues fill —
        # trainer blocked on a full output queue, feeder blocked on a full
        # input queue — and only feed_timeout breaks the embrace.
        feed_state = {"count": None, "error": None}
        cancel = threading.Event()

        def _feed():
            try:
                n = _feed_partition(iterator, mgr, "input", feed_timeout,
                                    cancel=cancel)
                _join_feed(mgr, "input", feed_timeout, on_error="raise")
                feed_state["count"] = n
            except BaseException as e:  # noqa: BLE001 - re-raised below
                feed_state["error"] = e

        feeder = threading.Thread(target=_feed, name="inference-feed",
                                  daemon=True)
        feeder.start()

        q_out = mgr.get_queue(qname)
        results = []
        deadline = time.monotonic() + feed_timeout
        try:
            while True:
                if feed_state["error"] is not None:
                    raise feed_state["error"]
                count = feed_state["count"]
                if count is not None and len(results) >= count:
                    break
                try:
                    batch = q_out.get(block=True, timeout=1.0)
                except _queue.Empty:
                    if mgr.get("state") in ("error", "terminating",
                                            "stopped"):
                        raise RuntimeError(
                            "inference aborted: trainer terminated with "
                            "{}/{} results delivered".format(
                                len(results), count if count is not None
                                else "?"))
                    if count is None:
                        # Feeding still in progress: its OWN per-put
                        # deadline (_feed_partition) governs liveness.
                        # The drain deadline arms once the feed is done,
                        # preserving the pre-concurrency semantics for
                        # trainers that emit only at partition end.
                        deadline = time.monotonic() + feed_timeout
                    elif time.monotonic() > deadline:
                        raise RuntimeError("inference results timeout")
                    continue
                q_out.task_done()
                deadline = time.monotonic() + feed_timeout
                if isinstance(batch, list):
                    results.extend(batch)
                else:
                    results.append(batch)
        except BaseException:
            cancel.set()  # the feeder must not outlive a failed task
            raise
        feeder.join()
        if feed_state["error"] is not None:
            raise feed_state["error"]
        return iter(results[:feed_state["count"]])

    return _inference


def shutdown(cluster_info, cluster_meta, queues=("input",), grace_secs=0):
    """Shutdown closure, one per executor: surface trainer errors, stop the
    feed, join the background trainer.

    Reference: ``TFSparkNode.shutdown`` → ``_shutdown`` (SURVEY.md §3.5).
    Raises on the executor if the trainer pushed an error — the driver's
    ``cluster.shutdown()`` re-raises it (error-propagation contract).
    """

    def _shutdown(iterator):
        for _ in iterator:
            pass
        mgr = _get_manager(cluster_info, cluster_meta, _local_executor_id())
        # End-of-feed marker unblocks DataFeed.next_batch deterministically.
        # Bounded put: a full channel means the trainer stopped consuming —
        # it will see the state flip below instead.
        for qname in queues:
            ring = _feed_ring(qname)
            try:
                if ring is not None:
                    ring.write_obj(marker.EndFeed(), timeout=5.0)
                else:
                    mgr.get_queue(qname).put(marker.EndFeed(), block=True,
                                             timeout=5.0)
            except Exception:
                pass
        if mgr.get("state") == "running":
            mgr.set("state", "terminating")

        st = _state()
        proc = st.get("trainer_proc")
        we_terminated = False
        if proc is not None:
            # Progress-aware join: the grace window is a NO-PROGRESS bound,
            # not a wall-clock cap. While the trainer's DataFeed heartbeat
            # (kv "feed_hb", a batches-served counter) keeps advancing,
            # the deadline re-arms — a trainer slowly draining a deep feed
            # backlog (slow steps: big models, remote-tunnel dispatch) is
            # alive, not wedged. Found on-chip in round 5: a hard 60s join
            # killed a live trainer whose steps ran ~4s/batch over the
            # PJRT tunnel. An explicit grace_secs is authoritative (tests
            # use small ones); the 60s floor applies only to the default.
            # Hard floor of 5s regardless: the heartbeat is throttled to
            # one publish per 2s, so a window at or under the throttle
            # structurally cannot observe a live trainer's progress.
            grace = grace_secs if grace_secs and grace_secs > 0 else 60
            grace = max(grace, 5)
            def _hb():
                try:
                    return mgr.get("feed_hb")
                except Exception:  # noqa: BLE001 - broker may be gone
                    return None
            last_hb = _hb()
            deadline = time.monotonic() + grace
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                proc.join(timeout=min(2.0, remaining))
                if not proc.is_alive():
                    break
                hb = _hb()
                if hb is not None and hb != last_hb:
                    last_hb = hb
                    deadline = time.monotonic() + grace
            if proc.is_alive():
                logger.warning("trainer pid %d unresponsive (no feed "
                               "progress for %.0fs); terminating",
                               proc.pid, grace)
                we_terminated = True
                proc.terminate()
                proc.join(timeout=10)
                if proc.is_alive():
                    # SIGTERM can't be delivered to a process wedged in a
                    # C-level call (the very mode that gets here); leaking
                    # it would hold the chip and the shm ring open.
                    logger.warning("trainer pid %d survived SIGTERM; "
                                   "killing", proc.pid)
                    proc.kill()
                    proc.join(timeout=5)
        tb_pid = st.get("tb_pid")
        if tb_pid:
            try:
                os.kill(tb_pid, 15)
            except OSError:
                pass
        ring = st.pop("shm_ring", None)
        if ring is not None:
            ring.unlink()
            ring.close()
        st.pop("cluster_id", None)

        # Error surfacing: anything on the error queue fails this task.
        errors = []
        try:
            eq = mgr.get_queue("error")
            while True:
                try:
                    errors.append(eq.get(block=False))
                    eq.task_done()
                except _queue.Empty:
                    break
        except Exception:
            pass
        # A trainer killed in the shutdown window can race the watchdog's
        # state check and report nothing — its exit code is still evidence.
        if (proc is not None and not errors and not we_terminated
                and proc.exitcode not in (0, None)):
            errors.append("trainer exited with code {} without reporting "
                          "an error (killed?)".format(proc.exitcode))

        # Final supervision beat, SYNCHRONOUS and best-effort: popping
        # cluster_id above silenced the beat thread, and a failure whose
        # whole window (crash -> this teardown) fits inside one beat
        # interval would otherwise never ride a beat at all — the
        # supervisor would see only an unattributable shutdown error.
        # This task is still running, so the driver's shutdown .get() is
        # still blocked and the reservation server is provably alive:
        # the terminal evidence (state, exit code) lands in the lease
        # BEFORE the error below reaches the driver.
        try:
            exit_code = None if proc is None else proc.exitcode
            # bounded connect (PR 19): "provably alive" above assumes
            # the driver is healthy — a CRASHED reservation server
            # must not wedge executor teardown for the OS timeout
            fc = reservation.Client(tuple(cluster_meta["server_addr"]),
                                    connect_timeout=5)
            try:
                # the FULL payload, not a minimal one: a beat REPLACES
                # the lease payload wholesale, and the goodput plane's
                # driver-side harvest reads the metrics snapshot off
                # the LAST lease — a final beat that dropped "metrics"
                # would erase the trainer's final accounting flush
                payload = _beat_payload(mgr, _local_executor_id())
                payload.update({
                    "trainer_exit": exit_code,
                    "trainer_alive": False if proc is not None else None,
                    "final": True, "errors": len(errors)})
                fc.beat(_local_executor_id(), payload)
            finally:
                fc.close()
        except Exception:  # noqa: BLE001 - server may already be gone
            pass

        if errors:
            raise RuntimeError(
                "trainer on executor {} failed:\n{}".format(
                    _local_executor_id(), "\n---\n".join(str(e) for e in errors)))

    return _shutdown
