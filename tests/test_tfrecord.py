"""TFRecord codec tests, with installed TensorFlow as the format oracle.

Reference test analog: ``tests/test_dfutil.py`` (SURVEY.md §4) — the
round-trip assertions; plus direct cross-validation of our TF-free codec
against tf.train.Example / tf.io.TFRecordWriter, which the reference got
for free from the tensorflow-hadoop JAR.
"""

import numpy as np
import pytest

from tensorflowonspark_tpu import tfrecord


def tf():
    return pytest.importorskip("tensorflow")


SAMPLE = {
    "label": [7],
    "weights": [0.5, -1.25, 3.0],
    "name": [b"hello"],
    "image": [bytes(range(16))],
    "ids": [1, -2, 3_000_000_000],
}


def test_example_roundtrip_self():
    data = tfrecord.encode_example(SAMPLE)
    parsed = tfrecord.parse_example(data)
    assert parsed["label"] == ("int64", [7])
    kind, vals = parsed["weights"]
    assert kind == "float" and np.allclose(vals, [0.5, -1.25, 3.0])
    assert parsed["name"] == ("bytes", [b"hello"])
    assert parsed["image"] == ("bytes", [bytes(range(16))])
    assert parsed["ids"] == ("int64", [1, -2, 3_000_000_000])


def test_encode_matches_tensorflow_parse():
    """TF must parse our bytes identically."""
    _tf = tf()
    data = tfrecord.encode_example(SAMPLE)
    ex = _tf.train.Example()
    ex.ParseFromString(data)
    f = ex.features.feature
    assert list(f["label"].int64_list.value) == [7]
    assert np.allclose(list(f["weights"].float_list.value), [0.5, -1.25, 3.0])
    assert list(f["name"].bytes_list.value) == [b"hello"]
    assert list(f["ids"].int64_list.value) == [1, -2, 3_000_000_000]


def test_parse_matches_tensorflow_encode():
    """We must parse TF's bytes identically (TF uses unpacked repeated)."""
    _tf = tf()
    ex = _tf.train.Example(features=_tf.train.Features(feature={
        "label": _tf.train.Feature(
            int64_list=_tf.train.Int64List(value=[3, -9])),
        "score": _tf.train.Feature(
            float_list=_tf.train.FloatList(value=[1.5, 2.5])),
        "blob": _tf.train.Feature(
            bytes_list=_tf.train.BytesList(value=[b"\x00\xff"])),
    }))
    parsed = tfrecord.parse_example(ex.SerializeToString())
    assert parsed["label"] == ("int64", [3, -9])
    kind, vals = parsed["score"]
    assert kind == "float" and np.allclose(vals, [1.5, 2.5])
    assert parsed["blob"] == ("bytes", [b"\x00\xff"])


def test_tfrecord_file_interop(tmp_path):
    """Files we write are readable by tf.data.TFRecordDataset & vice versa."""
    _tf = tf()
    ours = str(tmp_path / "ours.tfrecord")
    with tfrecord.TFRecordWriter(ours) as w:
        for i in range(5):
            w.write(tfrecord.encode_example({"i": [i]}))
    got = [bytes(r.numpy()) for r in _tf.data.TFRecordDataset(ours)]
    assert len(got) == 5
    assert tfrecord.parse_example(got[3])["i"] == ("int64", [3])

    theirs = str(tmp_path / "theirs.tfrecord")
    with _tf.io.TFRecordWriter(theirs) as w:
        for i in range(4):
            ex = _tf.train.Example(features=_tf.train.Features(feature={
                "i": _tf.train.Feature(
                    int64_list=_tf.train.Int64List(value=[i]))}))
            w.write(ex.SerializeToString())
    rows = list(tfrecord.read_examples(theirs))
    assert [r["i"][1][0] for r in rows] == [0, 1, 2, 3]


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        w.write(b"payload-bytes")
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="crc"):
        list(tfrecord.tfrecord_iterator(path))


def test_native_codec_available():
    """The C codec must build on this image (g++ is baked in); elsewhere
    the pure-python path is the documented degradation."""
    from tensorflowonspark_tpu import _tfrecord_native
    assert _tfrecord_native.available()


def test_native_crc_matches_python():
    from tensorflowonspark_tpu import _tfrecord_native
    for blob in (b"", b"a", bytes(range(256)) * 3, b"x" * 999,
                 b"\x00" * 64):
        assert _tfrecord_native.masked_crc32c(blob) == \
            tfrecord.masked_crc32c(blob), blob[:8]


def test_native_iterator_matches_python(tmp_path, monkeypatch):
    """Both read paths yield byte-identical records."""
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        for i in range(20):
            w.write(tfrecord.encode_example(
                {"i": [i], "w": [0.5 * i], "s": [b"r%d" % i]}))
    monkeypatch.setattr(tfrecord, "_NATIVE", True)
    native = list(tfrecord.tfrecord_iterator(path))
    monkeypatch.setattr(tfrecord, "_NATIVE", False)
    pure = list(tfrecord.tfrecord_iterator(path))
    assert native == pure
    assert len(native) == 20
    # the public iterator contract is host-independent: bytes on BOTH
    # paths (advisor r4 — memoryview leaked only on native-enabled hosts)
    assert all(type(r) is bytes for r in native)
    assert all(type(r) is bytes for r in pure)


def test_native_corruption_and_truncation(tmp_path, monkeypatch):
    monkeypatch.setattr(tfrecord, "_NATIVE", True)
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        w.write(b"payload-bytes")
    raw = open(path, "rb").read()

    bad = bytearray(raw)
    bad[14] ^= 0xFF  # payload byte -> data crc mismatch
    open(path, "wb").write(bytes(bad))
    with pytest.raises(ValueError, match="crc"):
        list(tfrecord.tfrecord_iterator(path))

    bad = bytearray(raw)
    bad[9] ^= 0xFF  # length crc itself
    open(path, "wb").write(bytes(bad))
    with pytest.raises(ValueError, match="crc"):
        list(tfrecord.tfrecord_iterator(path))

    open(path, "wb").write(raw[:-2])  # truncated trailing crc
    with pytest.raises(ValueError, match="[Tt]runcat"):
        list(tfrecord.tfrecord_iterator(path))


def test_read_batch_dense_schema(tmp_path, monkeypatch):
    """read_batch: native and pure python agree, and a dense-schema
    violation raises on both paths."""
    path = str(tmp_path / "dense.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        for i in range(32):
            w.write(tfrecord.encode_example(
                {"dense": [float(i), i + 0.5, -i * 2.0],
                 "label": [i % 3]}))
    schema = {"dense": ("float32", 3), "label": ("int64", 1)}

    monkeypatch.setattr(tfrecord, "_NATIVE", True)
    native = tfrecord.read_batch(path, schema)
    monkeypatch.setattr(tfrecord, "_NATIVE", False)
    pure = tfrecord.read_batch(path, schema)
    for name in schema:
        np.testing.assert_array_equal(native[name], pure[name])
    assert native["dense"].shape == (32, 3)
    assert native["dense"].dtype == np.float32
    assert native["label"].dtype == np.int64
    assert native["label"][5, 0] == 5 % 3

    for use_native in (True, False):
        monkeypatch.setattr(tfrecord, "_NATIVE", use_native)
        with pytest.raises(ValueError, match="feature"):
            tfrecord.read_batch(path, {"dense": ("float32", 4),
                                       "label": ("int64", 1)})
        with pytest.raises(ValueError, match="feature"):
            tfrecord.read_batch(path, {"missing": ("int64", 1)})


def test_pipe_backed_stream_uses_streaming_path(tmp_path):
    """A non-regular-file opener (pipe: fileno fstats size 0) must NOT
    read as an empty file via the native mmap path — it streams."""
    import os as _os
    import threading

    from tensorflowonspark_tpu import fs

    payload_buf = []
    with tfrecord.TFRecordWriter(str(tmp_path / "t.tfrecord")) as w:
        w.write(tfrecord.encode_example({"i": [41]}))
        w.write(tfrecord.encode_example({"i": [42]}))
    payload = open(str(tmp_path / "t.tfrecord"), "rb").read()
    payload_buf.append(payload)

    r, w_fd = _os.pipe()

    def _writer():
        _os.write(w_fd, payload)
        _os.close(w_fd)

    t = threading.Thread(target=_writer)
    t.start()
    fs.register_filesystem("pipe", lambda p, m: _os.fdopen(r, "rb"))
    try:
        rows = list(tfrecord.read_examples("pipe://x"))
    finally:
        fs.unregister_filesystem("pipe")
        t.join()
    assert [row["i"][1][0] for row in rows] == [41, 42]


def test_first_record_lazy(tmp_path):
    path = str(tmp_path / "f.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        for i in range(5):
            w.write(tfrecord.encode_example({"i": [i]}))
    first = tfrecord.first_record(path)
    assert tfrecord.parse_example(first)["i"] == ("int64", [0])
    open(path, "wb").write(b"")
    assert tfrecord.first_record(path) is None


def test_read_batch_tf_written_file(tmp_path):
    """Native batch decode reads TF-written packed/unpacked wire forms."""
    _tf = tf()
    path = str(tmp_path / "tfw.tfrecord")
    with _tf.io.TFRecordWriter(path) as w:
        for i in range(6):
            ex = _tf.train.Example(features=_tf.train.Features(feature={
                "f": _tf.train.Feature(float_list=_tf.train.FloatList(
                    value=[i * 1.0, i * 2.0])),
                "l": _tf.train.Feature(int64_list=_tf.train.Int64List(
                    value=[i, -i, 3_000_000_000 + i]))}))
            w.write(ex.SerializeToString())
    out = tfrecord.read_batch(path, {"f": ("float32", 2),
                                     "l": ("int64", 3)})
    np.testing.assert_allclose(out["f"][:, 1], np.arange(6) * 2.0)
    assert out["l"][4, 2] == 3_000_000_004
    assert out["l"][3, 1] == -3


def test_dfutil_string_arrays_and_empty_parts(tmp_path, request):
    """array<string> round-trips; empty part files don't break schema
    inference; variable-length under scalar dtype raises."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.engine import Context

    sc = Context(num_executors=2, work_root=str(tmp_path / "eng2"))
    request.addfinalizer(sc.stop)
    rows = [{"toks": ["a", "b-%d" % i], "n": i} for i in range(6)]
    df = sc.createDataFrame(rows, num_slices=2)
    out = str(tmp_path / "recs")
    assert dfutil.saveAsTFRecords(df, out) == 6
    # prepend an empty part file: schema inference must skip it
    open(out + "/part-00000a", "wb").close()
    import os
    os.rename(out + "/part-00000a", out + "/part-.empty")
    got = sorted(dfutil.loadTFRecords(sc, out).collect(),
                 key=lambda r: r["n"])
    assert got[3]["toks"] == ["a", "b-3"]

    # scalar-inferred column fed variable-length data -> explicit error
    conv = dfutil.fromTFExample(schema=[("v", "int64")])
    from tensorflowonspark_tpu import tfrecord as tfr
    bad = tfr.encode_example({"v": [1, 2]})
    with pytest.raises(ValueError, match="array<>"):
        list(conv([bad]))


def test_dfutil_roundtrip(tmp_path, request):
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.engine import Context

    sc = Context(num_executors=2, work_root=str(tmp_path / "engine"))
    request.addfinalizer(sc.stop)
    rows = [{"label": i % 10, "weight": float(i) / 4.0,
             "text": "row-%d" % i, "vec": [float(i), float(i + 1)]}
            for i in range(20)]
    df = sc.createDataFrame(rows, num_slices=3)
    assert sorted(df.columns) == ["label", "text", "vec", "weight"]

    out = str(tmp_path / "records")
    n = dfutil.saveAsTFRecords(df, out)
    assert n == 20

    df2 = dfutil.loadTFRecords(sc, out)
    got = sorted(df2.collect(), key=lambda r: r["label"] * 100 + r["weight"])
    want = sorted(rows, key=lambda r: r["label"] * 100 + r["weight"])
    assert len(got) == 20
    for g, w in zip(got, want):
        assert g["label"] == w["label"]
        assert abs(g["weight"] - w["weight"]) < 1e-6
        assert g["text"] == w["text"]
        assert np.allclose(g["vec"], w["vec"])


def test_fuzz_native_vs_python_roundtrip(tmp_path, monkeypatch):
    """Seeded fuzz: random feature dicts (empty lists, zero-length
    bytes, negative/64-bit ints, float specials, many features) written
    once, then parsed identically by the native and pure-python paths."""
    rng = np.random.RandomState(1234)

    def rand_value(kind):
        n = int(rng.randint(0, 6))
        if kind == 0:  # bytes, incl. zero-length blobs
            return [bytes(rng.randint(0, 256, size=rng.randint(0, 32),
                                      dtype=np.uint8).tobytes())
                    for _ in range(n)]
        if kind == 1:  # floats incl. specials
            pool = [0.0, -0.0, 1.5e38, -1.5e-38, 3.25, -7.0]
            return [float(pool[rng.randint(len(pool))]) for _ in range(n)]
        # int64 incl. negatives and 2^62-scale magnitudes
        pool = [0, 1, -1, 2**31, -(2**31), 2**62, -(2**62), 255]
        return [int(pool[rng.randint(len(pool))]) for _ in range(n)]

    path = str(tmp_path / "fuzz.tfrecord")
    examples = []
    with tfrecord.TFRecordWriter(path) as w:
        for _ in range(200):
            feats = {}
            for j in range(int(rng.randint(0, 8))):
                feats["f%d_%d" % (j, rng.randint(3))] = rand_value(
                    int(rng.randint(3)))
            examples.append(feats)
            w.write(tfrecord.encode_example(feats))

    monkeypatch.setattr(tfrecord, "_NATIVE", True)
    native = [tfrecord.parse_example(r)
              for r in tfrecord.tfrecord_iterator(path)]
    monkeypatch.setattr(tfrecord, "_NATIVE", False)
    pure = [tfrecord.parse_example(r)
            for r in tfrecord.tfrecord_iterator(path)]
    assert len(native) == len(pure) == 200
    for a, b in zip(native, pure):
        assert a.keys() == b.keys()
        for name in a:
            ka, va = a[name]
            kb, vb = b[name]
            assert ka == kb
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
