"""TFRecord codec tests, with installed TensorFlow as the format oracle.

Reference test analog: ``tests/test_dfutil.py`` (SURVEY.md §4) — the
round-trip assertions; plus direct cross-validation of our TF-free codec
against tf.train.Example / tf.io.TFRecordWriter, which the reference got
for free from the tensorflow-hadoop JAR.
"""

import numpy as np
import pytest

from tensorflowonspark_tpu import tfrecord


def tf():
    return pytest.importorskip("tensorflow")


SAMPLE = {
    "label": [7],
    "weights": [0.5, -1.25, 3.0],
    "name": [b"hello"],
    "image": [bytes(range(16))],
    "ids": [1, -2, 3_000_000_000],
}


def test_example_roundtrip_self():
    data = tfrecord.encode_example(SAMPLE)
    parsed = tfrecord.parse_example(data)
    assert parsed["label"] == ("int64", [7])
    kind, vals = parsed["weights"]
    assert kind == "float" and np.allclose(vals, [0.5, -1.25, 3.0])
    assert parsed["name"] == ("bytes", [b"hello"])
    assert parsed["image"] == ("bytes", [bytes(range(16))])
    assert parsed["ids"] == ("int64", [1, -2, 3_000_000_000])


def test_encode_matches_tensorflow_parse():
    """TF must parse our bytes identically."""
    _tf = tf()
    data = tfrecord.encode_example(SAMPLE)
    ex = _tf.train.Example()
    ex.ParseFromString(data)
    f = ex.features.feature
    assert list(f["label"].int64_list.value) == [7]
    assert np.allclose(list(f["weights"].float_list.value), [0.5, -1.25, 3.0])
    assert list(f["name"].bytes_list.value) == [b"hello"]
    assert list(f["ids"].int64_list.value) == [1, -2, 3_000_000_000]


def test_parse_matches_tensorflow_encode():
    """We must parse TF's bytes identically (TF uses unpacked repeated)."""
    _tf = tf()
    ex = _tf.train.Example(features=_tf.train.Features(feature={
        "label": _tf.train.Feature(
            int64_list=_tf.train.Int64List(value=[3, -9])),
        "score": _tf.train.Feature(
            float_list=_tf.train.FloatList(value=[1.5, 2.5])),
        "blob": _tf.train.Feature(
            bytes_list=_tf.train.BytesList(value=[b"\x00\xff"])),
    }))
    parsed = tfrecord.parse_example(ex.SerializeToString())
    assert parsed["label"] == ("int64", [3, -9])
    kind, vals = parsed["score"]
    assert kind == "float" and np.allclose(vals, [1.5, 2.5])
    assert parsed["blob"] == ("bytes", [b"\x00\xff"])


def test_tfrecord_file_interop(tmp_path):
    """Files we write are readable by tf.data.TFRecordDataset & vice versa."""
    _tf = tf()
    ours = str(tmp_path / "ours.tfrecord")
    with tfrecord.TFRecordWriter(ours) as w:
        for i in range(5):
            w.write(tfrecord.encode_example({"i": [i]}))
    got = [bytes(r.numpy()) for r in _tf.data.TFRecordDataset(ours)]
    assert len(got) == 5
    assert tfrecord.parse_example(got[3])["i"] == ("int64", [3])

    theirs = str(tmp_path / "theirs.tfrecord")
    with _tf.io.TFRecordWriter(theirs) as w:
        for i in range(4):
            ex = _tf.train.Example(features=_tf.train.Features(feature={
                "i": _tf.train.Feature(
                    int64_list=_tf.train.Int64List(value=[i]))}))
            w.write(ex.SerializeToString())
    rows = list(tfrecord.read_examples(theirs))
    assert [r["i"][1][0] for r in rows] == [0, 1, 2, 3]


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        w.write(b"payload-bytes")
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="crc"):
        list(tfrecord.tfrecord_iterator(path))


def test_dfutil_string_arrays_and_empty_parts(tmp_path, request):
    """array<string> round-trips; empty part files don't break schema
    inference; variable-length under scalar dtype raises."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.engine import Context

    sc = Context(num_executors=2, work_root=str(tmp_path / "eng2"))
    request.addfinalizer(sc.stop)
    rows = [{"toks": ["a", "b-%d" % i], "n": i} for i in range(6)]
    df = sc.createDataFrame(rows, num_slices=2)
    out = str(tmp_path / "recs")
    assert dfutil.saveAsTFRecords(df, out) == 6
    # prepend an empty part file: schema inference must skip it
    open(out + "/part-00000a", "wb").close()
    import os
    os.rename(out + "/part-00000a", out + "/part-.empty")
    got = sorted(dfutil.loadTFRecords(sc, out).collect(),
                 key=lambda r: r["n"])
    assert got[3]["toks"] == ["a", "b-3"]

    # scalar-inferred column fed variable-length data -> explicit error
    conv = dfutil.fromTFExample(schema=[("v", "int64")])
    from tensorflowonspark_tpu import tfrecord as tfr
    bad = tfr.encode_example({"v": [1, 2]})
    with pytest.raises(ValueError, match="array<>"):
        list(conv([bad]))


def test_dfutil_roundtrip(tmp_path, request):
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.engine import Context

    sc = Context(num_executors=2, work_root=str(tmp_path / "engine"))
    request.addfinalizer(sc.stop)
    rows = [{"label": i % 10, "weight": float(i) / 4.0,
             "text": "row-%d" % i, "vec": [float(i), float(i + 1)]}
            for i in range(20)]
    df = sc.createDataFrame(rows, num_slices=3)
    assert sorted(df.columns) == ["label", "text", "vec", "weight"]

    out = str(tmp_path / "records")
    n = dfutil.saveAsTFRecords(df, out)
    assert n == 20

    df2 = dfutil.loadTFRecords(sc, out)
    got = sorted(df2.collect(), key=lambda r: r["label"] * 100 + r["weight"])
    want = sorted(rows, key=lambda r: r["label"] * 100 + r["weight"])
    assert len(got) == 20
    for g, w in zip(got, want):
        assert g["label"] == w["label"]
        assert abs(g["weight"] - w["weight"]) < 1e-6
        assert g["text"] == w["text"]
        assert np.allclose(g["vec"], w["vec"])
