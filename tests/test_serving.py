"""Language-neutral serving endpoint (serving.py) — the L0 JVM-API analog.

Reference: the Scala inference API let JVM Spark jobs run inference; the
TPU-native replacement is TF-Serving-shaped REST (SURVEY.md §2 L0 row),
callable from Scala/Java with plain HTTP. These tests speak raw HTTP via
urllib — exactly what a non-Python client does.
"""

import json
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import export, serving


@pytest.fixture()
def server(tmp_path):
    def apply_fn(variables, batch):
        return {"y": batch["x"] @ variables["w"] + variables["b"]}

    variables = {"w": jnp.asarray([[2.0], [1.0]]), "b": jnp.asarray([1.0])}
    d = str(tmp_path / "export")
    export.save_model(d, apply_fn, variables,
                      signature={"inputs": ["x"], "outputs": ["y"]})
    with serving.ModelServer(d, name="lin", port=0) as srv:
        host, port = srv._host, srv._port
        yield "http://%s:%d" % (host, port)


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_status_and_metadata(server):
    code, status = _get(server + "/v1/models/lin")
    assert code == 200
    assert status["model_version_status"][0]["state"] == "AVAILABLE"

    code, meta = _get(server + "/v1/models/lin/metadata")
    assert code == 200
    assert meta["model_spec"]["name"] == "lin"
    assert meta["metadata"]["signature_def"]["inputs"] == ["x"]


def test_predict_row_format(server):
    # TF-Serving row format: named instance dicts
    code, out = _post(server + "/v1/models/lin:predict",
                      {"instances": [{"x": [1.0, 2.0]}, {"x": [3.0, 0.0]}]})
    assert code == 200
    np.testing.assert_allclose(out["predictions"], [[5.0], [7.0]])

    # unnamed instances resolve through the single-input signature
    code, out = _post(server + "/v1/models/lin:predict",
                      {"instances": [[1.0, 2.0], [3.0, 0.0]]})
    assert code == 200
    np.testing.assert_allclose(out["predictions"], [[5.0], [7.0]])


def test_predict_columnar_format(server):
    code, out = _post(server + "/v1/models/lin:predict",
                      {"inputs": {"x": [[1.0, 2.0], [0.0, 1.0]]}})
    assert code == 200
    np.testing.assert_allclose(out["outputs"], [[5.0], [2.0]])


def test_predict_bad_request(server):
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(server + "/v1/models/lin:predict", {"wrong": 1})
    assert err.value.code == 400
    body = json.loads(err.value.read())
    assert "instances" in body["error"]

    with pytest.raises(urllib.error.HTTPError) as err:
        _post(server + "/v1/models/lin:predict",
              {"instances": [{"x": [1.0]}, {"z": [1.0]}]})
    assert err.value.code == 400


def test_predict_ragged_rows_are_400(server):
    # rows of differing lengths are the CLIENT's malformed request —
    # they must map to 400, not surface as a 500 from np.asarray or the
    # model apply (advisor r4 finding)
    for payload in (
            {"instances": [[1.0, 2.0], [3.0]]},
            {"instances": [{"x": [1.0, 2.0]}, {"x": [3.0]}]},
            {"inputs": {"x": [[1.0, 2.0], [3.0]]}},
            {"instances": [[1.0, "not-a-number-row"], [3.0, 0.0]]}):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server + "/v1/models/lin:predict", payload)
        assert err.value.code == 400, payload
        body = json.loads(err.value.read())
        assert "error" in body


def test_unknown_model_404(server):
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(server + "/v1/models/nope/metadata")
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(server + "/v1/models/nope:predict", {"instances": [[1.0]]})
    assert err.value.code == 404


def test_serving_generative_model(tmp_path):
    """Generation behind the REST surface: the exported apply_fn wraps
    the KV-cache decode loop, so a JVM-style HTTP client gets token
    continuations from a plain :predict call."""
    import jax

    from tensorflowonspark_tpu import generation
    from tensorflowonspark_tpu.models.decoder import DecoderLM

    dec = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                    max_len=16, decode=True)
    train = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                      max_len=16, decode=False)
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]

    def apply_fn(variables, batch):
        tokens = generation.generate_jit(
            dec, variables["params"], jnp.asarray(batch["prompt"]),
            max_new_tokens=4)
        return {"tokens": tokens}

    d = str(tmp_path / "lm-export")
    export.save_model(d, apply_fn, {"params": params},
                      signature={"inputs": ["prompt"],
                                 "outputs": ["tokens"]})
    with serving.ModelServer(d, name="lm", port=0) as srv:
        url = "http://%s:%d" % (srv._host, srv._port)
        code, out = _post(url + "/v1/models/lm:predict",
                          {"inputs": {"prompt": [[1, 2, 3]]}})
    assert code == 200
    toks = out["outputs"]
    assert len(toks) == 1 and len(toks[0]) == 7  # 3 prompt + 4 new
    assert toks[0][:3] == [1, 2, 3]
    assert all(0 <= t < 8 for t in toks[0])


def test_serving_quantized_widedeep(tmp_path):
    """The recommender serving journey: f32 params -> int8 tables
    (quantize_embeddings) -> export -> REST predict, with logits
    tracking the f32 model (SURVEY §2.2 quantized embedding lookups)."""
    import jax

    from tensorflowonspark_tpu.models import widedeep

    model = widedeep.WideDeep(hash_buckets=32, embed_dim=8,
                              mlp_sizes=(16,), dtype=jnp.float32)
    rng = np.random.RandomState(0)
    dense = rng.rand(4, 13).astype(np.float32)
    cat = rng.randint(0, 32, (4, 26))
    params = model.init(jax.random.PRNGKey(0), dense, cat)["params"]
    ref = np.asarray(model.apply({"params": params}, dense, cat))

    slim, quant = widedeep.quantize_embeddings(params)
    qmodel = widedeep.WideDeep(hash_buckets=32, embed_dim=8,
                               mlp_sizes=(16,), dtype=jnp.float32,
                               quantized=True)

    def apply_fn(variables, batch):
        return {"ctr_logit": qmodel.apply(
            variables, np.asarray(batch["dense"], np.float32),
            np.asarray(batch["cat"], np.int32))}

    d = str(tmp_path / "wd-q")
    export.save_model(d, apply_fn, {"params": slim, "quant": quant},
                      signature={"inputs": ["dense", "cat"],
                                 "outputs": ["ctr_logit"]})
    with serving.ModelServer(d, name="wd", port=0) as srv:
        url = "http://%s:%d" % (srv._host, srv._port)
        code, out = _post(url + "/v1/models/wd:predict",
                          {"inputs": {"dense": dense.tolist(),
                                      "cat": cat.tolist()}})
    assert code == 200
    np.testing.assert_allclose(out["outputs"], ref, rtol=0.05, atol=0.05)


def test_batching_window_coalesces_concurrent_generates(tmp_path):
    """VERDICT r4 task 8: parallel single-prompt clients against the
    generative path with a batching window — correct continuations,
    FEWER model calls than requests (the coalescing is real), p50
    latency recorded."""
    import statistics
    import threading
    import time

    import jax

    from tensorflowonspark_tpu import generation
    from tensorflowonspark_tpu.models.decoder import DecoderLM

    dec = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                    max_len=16, decode=True)
    train = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                      max_len=16, decode=False)
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    calls_file = str(tmp_path / "calls")

    def apply_fn(variables, batch, _calls=calls_file):
        with open(_calls, "a") as f:
            f.write("%d\n" % len(batch["prompt"]))
        tokens = generation.generate_jit(
            dec, variables["params"], jnp.asarray(batch["prompt"]),
            max_new_tokens=4)
        return {"tokens": tokens}

    d = str(tmp_path / "lm-export")
    export.save_model(d, apply_fn, {"params": params},
                      signature={"inputs": ["prompt"],
                                 "outputs": ["tokens"]})
    n = 12
    with serving.ModelServer(d, name="lm", port=0,
                             batch_window_ms=150) as srv:
        url = "http://%s:%d/v1/models/lm:predict" % (srv._host, srv._port)

        # warm the jit cache so the window measures batching, not compile
        _post(url, {"inputs": {"prompt": [[0, 1, 2]]}})
        open(calls_file, "w").close()

        latencies = [None] * n
        outs = [None] * n

        def call(i):
            t0 = time.monotonic()
            _, out = _post(url, {"inputs": {"prompt": [[1, 2, i % 8]]}})
            latencies[i] = time.monotonic() - t0
            outs[i] = out["outputs"][0]

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

    assert all(o is not None for o in outs)
    for i, o in enumerate(outs):
        assert len(o) == 7 and o[:3] == [1, 2, i % 8], (i, o)
        assert all(0 <= t < 8 for t in o)
    calls = [int(x) for x in open(calls_file).read().split()]
    # each model call is padded up to a power-of-two bucket (compile-
    # cache hygiene), so total rows >= requests and every size is 2^k
    assert sum(calls) >= n, calls
    assert all(c & (c - 1) == 0 for c in calls), calls
    assert len(calls) < n, \
        "window never coalesced: {} calls for {} requests".format(
            len(calls), n)
    p50 = statistics.median(latencies)
    print("batched generate: {} requests -> {} model calls "
          "(max batch {}), p50 latency {:.0f}ms".format(
              n, len(calls), max(calls), p50 * 1000))


def test_batching_window_mixed_signatures_and_errors(tmp_path):
    """Different-shape requests run in their own groups (results never
    change), and an apply failure reaches every coalesced client as its
    own 500 without killing the batcher."""
    import threading

    def apply_fn(variables, batch):
        x = np.asarray(batch["x"])
        if x.shape[1] == 3:
            raise RuntimeError("three-wide inputs are cursed")
        return {"y": x * 2.0}

    d = str(tmp_path / "export")
    export.save_model(d, apply_fn, {"w": jnp.zeros(1)},  # orbax: non-empty
                      signature={"inputs": ["x"], "outputs": ["y"]})
    with serving.ModelServer(d, name="m", port=0,
                             batch_window_ms=80) as srv:
        url = "http://%s:%d/v1/models/m:predict" % (srv._host, srv._port)
        codes = {}

        def call(key, payload):
            try:
                code, out = _post(url, payload)
            except urllib.error.HTTPError as e:
                code, out = e.code, None
            codes[key] = (code, out)

        threads = [
            threading.Thread(target=call, args=(
                "w2-%d" % i, {"inputs": {"x": [[1.0 * i, 2.0]]}}))
            for i in range(3)
        ] + [
            threading.Thread(target=call, args=(
                "w3", {"inputs": {"x": [[1.0, 2.0, 3.0]]}})),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        # the cursed signature 500s alone; the 2-wide group still works
        assert codes["w3"][0] == 500
        for i in range(3):
            code, out = codes["w2-%d" % i]
            assert code == 200
            assert out["outputs"] == [[2.0 * i, 4.0]]
        # batcher survived the failure: a fresh request still serves
        code, out = _post(url, {"inputs": {"x": [[5.0, 5.0]]}})
        assert code == 200 and out["outputs"] == [[10.0, 10.0]]


def test_concurrent_predicts(server):
    """The single-owner lock serializes; concurrent clients all succeed."""
    import threading

    results = []

    def call(i):
        _, out = _post(server + "/v1/models/lin:predict",
                       {"instances": [[float(i), 0.0]]})
        results.append((i, out["predictions"][0][0]))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == [(i, 2.0 * i + 1.0) for i in range(8)]


def test_generate_route_continuous_batching(tmp_path):
    """The :generate endpoint mounts a DecodeEngine (PR 2): concurrent
    single-prompt HTTP clients share the slot-structured decode loop
    and each gets exactly its solo-generate continuation — no window,
    no run-to-completion groups."""
    import threading

    import jax

    from tensorflowonspark_tpu import generation, serving as serving_mod
    from tensorflowonspark_tpu.models.decoder import DecoderLM

    dec = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                    max_len=24, decode=True)
    train = DecoderLM(vocab=8, hidden=16, num_heads=2, num_layers=1,
                      max_len=24, decode=False)
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 24), jnp.int32))["params"]
    engine = serving_mod.DecodeEngine(dec, params, slots=2)
    with serving_mod.ModelServer(None, name="lm", port=0,
                                 engine=engine) as srv:
        url = "http://%s:%d/v1/models/lm:generate" % (srv._host, srv._port)
        prompts = [[1, 2, (3 + i) % 8] for i in range(6)]
        outs = [None] * len(prompts)

        def call(i):
            _, out = _post(url, {"prompt": prompts[i],
                                 "max_new_tokens": 5})
            outs[i] = out["tokens"]

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)

        # multi-prompt body in one request, and validation surfaces 400
        _, multi = _post(url, {"prompt": prompts[:2], "max_new_tokens": 3})
        assert len(multi["tokens"]) == 2
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(url, {"max_new_tokens": 3})
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(url, {"prompt": [1, 2], "max_new_tokens": 999})
        assert err.value.code == 400
        # engine-only server: :predict refuses loudly, not a 500
        with pytest.raises(urllib.error.HTTPError) as err:
            _post("http://%s:%d/v1/models/lm:predict"
                  % (srv._host, srv._port), {"instances": [[1.0]]})
        assert err.value.code == 400

    for i, p in enumerate(prompts):
        solo = generation.generate_jit(dec, params,
                                       jnp.asarray([p], jnp.int32), 5)
        assert outs[i] == np.asarray(solo)[0].tolist(), i
    for a, b in zip(multi["tokens"], prompts[:2]):
        assert a[:3] == b
