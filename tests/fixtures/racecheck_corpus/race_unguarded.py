"""Racecheck fixture: known races that MUST flag (tests/test_analysis.py).

Parsed, never imported — the analyzer is purely syntactic.
"""

import threading


class Racy(object):
    """The guarded-attribute race shape: _count is mutated under
    _lock in inc() — so it is guarded — and mutated bare in the
    public reset() and in a private helper reached from an UNLOCKED
    public path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def inc(self):
        with self._lock:
            self._count += 1
            self._items.append(self._count)

    def reset(self):
        self._count = 0           # MUST FLAG: unguarded assign

    def bump_twice(self):
        self._bump()              # unlocked call site ...

    def _bump(self):
        self._count += 1          # MUST FLAG: reached unlocked

    def shrink(self):
        self._items.pop()         # MUST FLAG: unguarded mutator call


class CrossThread(object):
    """The cross-thread shape: _seen mutated lock-free both by the
    spawned loop and a public method; no lock exists at all."""

    def __init__(self):
        self._stop = threading.Event()
        self._seen = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="fixture-loop", daemon=True)

    def _loop(self):
        while not self._stop.is_set():
            self._seen += 1       # thread root ...

    def note(self):
        self._seen += 1           # MUST FLAG: ... and a public root

    def stop(self):
        self._stop.set()
        self._thread.join()
