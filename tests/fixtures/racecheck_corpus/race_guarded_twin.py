"""Racecheck fixture: the guarded TWIN of race_unguarded.py — same
shapes, every mutation provably under the lock (directly or through
the caller-holds-the-lock convention) — MUST pass clean."""

import threading


class Guarded(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def inc(self):
        with self._lock:
            self._count += 1
            self._items.append(self._count)

    def reset(self):
        with self._lock:
            self._count = 0

    def bump_twice(self):
        with self._lock:
            self._bump()          # caller holds the lock ...

    def _bump(self):
        self._count += 1          # ... so this is GUARDED (no flag)

    def shrink(self):
        with self._lock:
            self._items.pop()


class CrossThreadGuarded(object):
    """Thread + public writer sharing state, correctly: both sides
    take the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seen = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="fixture-loop", daemon=True)

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                self._seen += 1

    def note(self):
        with self._lock:
            self._seen += 1

    def stop(self):
        self._stop.set()
        self._thread.join()
