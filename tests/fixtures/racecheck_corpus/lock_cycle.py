"""Racecheck fixture: lock-order hazards that MUST flag, and an
ordered twin that must not."""

import threading


class Deadlocky(object):
    """A-under-B in one method, B-under-A in another — two threads
    taking these in opposite order deadlock. MUST FLAG lock-order."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass


class DeadlockyViaCall(object):
    """Same cycle, one leg hidden behind an intra-class call."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            self._take_b()

    def _take_b(self):
        with self._b:
            pass

    def backward(self):
        with self._b:
            with self._a:
                pass


class SelfNest(object):
    """Re-entering a non-reentrant Lock via a Condition alias —
    single-thread deadlock. MUST FLAG lock-self-nest."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def wedge(self):
        with self._lock:
            with self._cv:
                pass


class Ordered(object):
    """Consistent order everywhere — must pass clean."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            self._take_b()

    def _take_b(self):
        with self._b:
            pass
