"""Racecheck fixture: thread-lifecycle and taxonomy violations that
MUST flag."""

import threading


class Retriable(RuntimeError):
    pass


class Shed(Retriable):
    pass


def spawn_anonymous():
    # MUST FLAG thread-daemon + thread-name + thread-unjoined
    threading.Thread(target=print).start()


def spawn_named_no_daemon():
    # MUST FLAG thread-daemon (name present, daemon absent)
    t = threading.Thread(target=print, name="fixture-worker")
    t.start()
    t.join()


class Spawner(object):
    def start(self):
        # MUST FLAG thread-unjoined: no join on self._t anywhere
        self._t = threading.Thread(target=print, name="fixture-bg",
                                   daemon=True)
        self._t.start()


def swallow(fn):
    try:
        return fn()
    except Shed:
        pass  # MUST FLAG retriable-swallow: eaten, not mapped


def swallow_logged(fn, logger):
    try:
        return fn()
    except (Retriable, ValueError) as e:
        logger.warning("ignored: %s", e)  # MUST FLAG: logging != mapping
