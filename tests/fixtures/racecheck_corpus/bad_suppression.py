"""Racecheck fixture: a suppression with an EMPTY reason — the
grammar demands one, so this MUST flag bad-suppression."""

import threading


class EmptyReason(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self):
        with self._lock:
            self._n += 1

    def reset(self):
        self._n = 0  # tfos: unguarded()
