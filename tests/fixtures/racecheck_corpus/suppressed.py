"""Racecheck fixture: every violation carries a valid suppression —
MUST pass clean (the suppression grammar round-trip)."""

import threading


class Retriable(RuntimeError):
    pass


class RacySuppressed(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def inc(self):
        with self._lock:
            self._count += 1

    def reset(self):
        self._count = 0  # tfos: unguarded(fixture: single-writer by construction)


def spawn():
    # tfos: unjoined(fixture: fire-and-forget by design)
    threading.Thread(target=print, name="fixture-ff",
                     daemon=True).start()


def swallow(fn):
    try:
        return fn()
    except Retriable:  # tfos: swallow(fixture: best-effort probe, caller polls state())
        pass
