"""Serving fleet (PR 6): replica registry, metrics-driven router,
failover, half-open health, rolling drain.

Three layers, matching the module's design:

- PURE policy — ``fleet.route_order`` (least-loaded selection from
  gauge snapshots, stale-lease exclusion, deterministic tie-breaking)
  and the ``ReplicaHealth`` half-open state machine, table-driven with
  injected time, no sockets; plus the shared ``serving.retry_call``
  client retry policy (bounded backoff + full jitter, Retry-After
  floor, Retriable-only).
- SCHEMA pins — the stable ``replica_id`` identity on /healthz and
  /metrics (survives ``respawn()``), the reservation server's
  serving-role lease view (``serving_snapshot`` + the ``/stats``
  ``serving`` key), and the retriable-503 ``kind`` field the router
  classifies on.
- E2E — a 2-replica fleet over real HTTP (tier-1: routed requests are
  bitwise solo-identical, metrics expose per-replica labels), the
  3-replica rolling-drain weight-upgrade cycle under live traffic
  (slow), and the chaos leg: kill one replica's scheduler mid-stream,
  zero client-visible failures, supervised restart, MTTR recorded
  (chaos marker — collected by ``make chaos``).
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import (chaos, cluster, fleet, generation,
                                   paging, reservation, serving)
from tensorflowonspark_tpu.models.decoder import DecoderLM

V, H, NH, L, MAXLEN = 17, 32, 4, 2, 48


@pytest.fixture(scope="module")
def lm():
    train = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                      max_len=MAXLEN, decode=False)
    dec = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                    max_len=MAXLEN, decode=True)
    params = train.init(jax.random.PRNGKey(7),
                        jnp.zeros((2, MAXLEN), jnp.int32))["params"]
    return dec, params


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.disarm()


def _counts(eng):
    return eng.counters.snapshot()["counts"]


def _solo(dec, params, prompt, max_new):
    out = generation.generate_jit(
        dec, params, jnp.asarray([prompt], jnp.int32), max_new)
    return np.asarray(out)[0].tolist()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# -- serving.retry_call (shared client retry policy) -----------------------

def test_retry_call_retries_only_retriable():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        serving.retry_call(fn, attempts=5, sleep=lambda s: None)
    assert len(calls) == 1, "non-Retriable must propagate on first raise"


def test_retry_call_bounded_attempts_and_backoff_growth():
    delays = []
    calls = []

    def fn():
        calls.append(1)
        raise serving.Retriable("transient")

    with pytest.raises(serving.Retriable):
        serving.retry_call(fn, attempts=4, base_delay=0.1, max_delay=10.0,
                           sleep=delays.append, rng=lambda: 1.0)
    assert len(calls) == 4
    # rng=1.0 makes jitter deterministic: retry_after (Retriable's
    # default 1.0) floors every delay, plus the full anti-stampede
    # jitter fraction of the floor
    floor = 1.0 * (1.0 + serving.RETRY_AFTER_JITTER)
    assert delays == [pytest.approx(floor)] * 3


def test_retry_call_retry_after_jitter_spreads_synchronized_clients():
    """Two clients told the same Retry-After by one recovering replica
    must NOT re-arrive at the same instant: the floor gains up to
    RETRY_AFTER_JITTER of itself, drawn per client."""

    def fn():
        raise serving.Shed("busy", retry_after=2.0)

    def delays_for(draw):
        delays = []
        with pytest.raises(serving.Shed):
            serving.retry_call(fn, attempts=2, base_delay=0.01,
                               max_delay=10.0, sleep=delays.append,
                               rng=lambda: draw)
        return delays

    lo, hi = delays_for(0.0), delays_for(1.0)
    assert lo == [pytest.approx(2.0)], "zero draw keeps the exact floor"
    assert hi == [pytest.approx(2.0 * (1 + serving.RETRY_AFTER_JITTER))]
    assert hi[0] > lo[0], "different draws must spread the stampede"


def test_retry_call_full_jitter_bounded_by_envelope():
    delays = []

    def fn():
        e = serving.Retriable("transient")
        e.retry_after = None  # no server hint: pure jittered backoff
        raise e

    with pytest.raises(serving.Retriable):
        serving.retry_call(fn, attempts=4, base_delay=0.2, max_delay=10.0,
                           sleep=delays.append, rng=lambda: 0.5)
    assert delays == [pytest.approx(0.1), pytest.approx(0.2),
                      pytest.approx(0.4)]


def test_retry_call_honors_retry_after_floor_capped():
    delays = []

    def fn():
        raise serving.Shed("busy", retry_after=3.0)

    with pytest.raises(serving.Shed):
        serving.retry_call(fn, attempts=3, base_delay=0.01, max_delay=2.0,
                           sleep=delays.append, rng=lambda: 0.0)
    # Retry-After floors the jittered delay but caps at max_delay
    assert delays == [2.0, 2.0]


def test_retry_call_zero_retry_after_fails_over_immediately():
    delays = []
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise fleet.ReplicaUnavailable("next replica",
                                           retry_after=0.0)
        return "ok"

    # rng pinned to its MAX: the no-sleep contract must hold because
    # retry_after==0 skips the sleep entirely, not because the jitter
    # happened to draw zero
    assert serving.retry_call(fn, attempts=4, sleep=delays.append,
                              rng=lambda: 1.0) == "ok"
    assert delays == [], "failover with retry_after=0 must not sleep"


def test_http_retriable_mapping():
    e = serving.http_retriable(503, "7")
    assert isinstance(e, serving.Retriable) and e.retry_after == 7.0
    assert serving.http_retriable(429).retry_after == 0.5
    assert serving.http_retriable(503, "garbage").retry_after == 1.0
    for status in (200, 400, 404, 499, 500, 504):
        assert serving.http_retriable(status) is None


# -- route_order (pure dispatch policy) ------------------------------------

def _view(rid, age=0.1, alive=True, draining=False, queue_depth=0,
          slot_occupancy=0, queue_wait_ewma_s=0.0, inflight=0,
          state=fleet.ReplicaHealth.UP):
    return {"replica_id": rid, "age": age, "alive": alive,
            "draining": draining, "queue_depth": queue_depth,
            "slot_occupancy": slot_occupancy,
            "queue_wait_ewma_s": queue_wait_ewma_s,
            "inflight": inflight, "state": state}


def test_route_order_least_loaded():
    views = [_view("a", queue_depth=3),
             _view("b", slot_occupancy=1),
             _view("c", queue_depth=1, slot_occupancy=1)]
    assert fleet.route_order(views) == ["b", "c", "a"]


def test_route_order_router_inflight_counts_as_load():
    # the router's own open requests cover the beat-staleness window:
    # a burst dispatched 10ms ago is load even if no gauge shows it yet
    views = [_view("a", inflight=2), _view("b")]
    assert fleet.route_order(views) == ["b", "a"]


def test_route_order_queue_wait_breaks_equal_backlog():
    views = [_view("a", queue_depth=1, queue_wait_ewma_s=0.5),
             _view("b", queue_depth=1, queue_wait_ewma_s=0.1)]
    assert fleet.route_order(views) == ["b", "a"]


def test_route_order_deterministic_tie_break_by_id():
    views = [_view("r2"), _view("r0"), _view("r1")]
    assert fleet.route_order(views) == ["r0", "r1", "r2"]
    assert fleet.route_order(list(reversed(views))) == ["r0", "r1", "r2"]


def test_route_order_excludes_stale_dead_draining_down():
    views = [
        _view("stale", age=5.0),          # lease older than stale_after
        _view("no-lease", age=None),      # never beat
        _view("dead", alive=False),       # engine scheduler dead
        _view("retiring", draining=True),  # excludes itself via beat
        _view("down", state=fleet.ReplicaHealth.DOWN),
        _view("ok", queue_depth=9),
    ]
    assert fleet.route_order(views, stale_after=2.0) == ["ok"]


def test_route_order_probe_ranks_after_every_healthy():
    views = [_view("probe", state=fleet.ReplicaHealth.PROBE),
             _view("busy", queue_depth=50)]
    # even a heavily loaded healthy replica outranks an unverified one
    assert fleet.route_order(views) == ["busy", "probe"]


def test_route_order_empty_when_nothing_routable():
    assert fleet.route_order([_view("a", age=99.0)]) == []
    assert fleet.route_order([]) == []


# -- prefix/session affinity (PR 16; pure policy) --------------------------


def _digest_view(rid, chains=(), block_size=16, slots=0, **kw):
    """A replica view carrying a beat digest: ``chains`` is a list of
    (tokens, depth_blocks) pairs hashed the way the pool publishes."""
    v = _view(rid, **kw)
    v["slots"] = slots
    v["prefix_digest_block_size"] = block_size
    v["prefix_digest"] = [
        [paging.chain_digest(tokens, depth * block_size), depth]
        for tokens, depth in chains]
    v["digest_truncated"] = False
    return v


def test_digest_match_deepest_resident_chain():
    prompt = list(range(50))
    view = _digest_view("a", chains=[(prompt, 1), (prompt, 2)])
    # the deepest RESIDENT chain wins, capped by the prompt's own
    # shareable depth ((len-1)//block — a tail token never shares)
    assert fleet.digest_match(view, prompt) == 2
    assert fleet.digest_match(view, prompt[:17]) == 1
    assert fleet.digest_match(view, prompt[:16]) == 0  # all tail
    assert fleet.digest_match(view, [9] * 50) == 0     # different chain
    # zero schema (contiguous replica) and malformed entries are cold
    assert fleet.digest_match(_view("b"), prompt) == 0
    broken = _digest_view("c", chains=[(prompt, 1)])
    broken["prefix_digest"] = [["x"], None, ["h", "deep"]]
    assert fleet.digest_match(broken, prompt) == 0


def test_digest_match_respects_each_views_block_size():
    """Depth is counted in each view's OWN block size: the same
    resident token span reads as depth 2 on an 8-block replica and
    depth 1 on a 16-block one, and a prompt too short to fill a
    view's chain misses it entirely."""
    prompt = list(range(33))
    v8 = _digest_view("a", chains=[(prompt, 2)], block_size=8)
    v16 = _digest_view("b", chains=[(prompt, 2)], block_size=16)
    assert fleet.digest_match(v8, prompt) == 2
    assert fleet.digest_match(v16, prompt) == 2
    # 17 tokens share 2 full 8-blocks -> the SAME 16-token span the
    # 8-block replica registered; the 16-block replica's resident
    # chain is 32 tokens deep, which this prompt cannot reach
    assert fleet.digest_match(v8, prompt[:17]) == 2
    assert fleet.digest_match(v16, prompt[:17]) == 0


def test_affinity_order_promotes_hint_then_deepest_digest():
    prompt = list(range(40))
    views = [_view("a"),
             _digest_view("b", chains=[(prompt, 2)], queue_depth=1),
             _digest_view("c", chains=[(prompt, 1)], queue_depth=2)]
    matches = {rid: fleet.digest_match(v, prompt)
               for rid, v in (("b", views[1]), ("c", views[2]))}
    # digest only: deeper resident chain leads, cold least-loaded next
    assert fleet.affinity_order(views, matches) == ["b", "c", "a"]
    # a session hint outranks even a deeper digest match elsewhere
    assert fleet.affinity_order(views, matches, session_hint="c") == \
        ["c", "b", "a"]
    # no affinity inputs -> exactly route_order
    assert fleet.affinity_order(views) == fleet.route_order(views)


def test_affinity_load_guard_demotes_overloaded_warm_replica():
    prompt = list(range(40))
    warm = _digest_view("warm", chains=[(prompt, 2)], queue_depth=3,
                        slot_occupancy=2)  # backlog 5 over coldest 0
    views = [_view("cold"), warm]
    matches = {"warm": 2}
    order, info = fleet.affinity_plan(views, matches)
    assert order == ["cold", "warm"]
    assert info["guarded"] == ["warm"] and info["promoted"] == []
    # inside the guard the warm replica still wins
    warm2 = _digest_view("warm", chains=[(prompt, 2)], queue_depth=2,
                         slot_occupancy=2)
    order, info = fleet.affinity_plan([_view("cold"), warm2], matches)
    assert order == ["warm", "cold"] and info["promoted"] == ["warm"]
    # slot saturation with a standing queue guards regardless of the
    # backlog delta (queue growth on a full replica is the hotspot)
    sat = _digest_view("warm", chains=[(prompt, 2)], slots=2,
                       slot_occupancy=2, queue_depth=1)
    order, info = fleet.affinity_plan(
        [_view("cold", queue_depth=2), sat], matches)
    assert info["guarded"] == ["warm"]
    assert order == fleet.route_order([_view("cold", queue_depth=2),
                                       sat])


def test_affinity_never_promotes_probe_and_fails_over_cold():
    prompt = list(range(40))
    probe = _digest_view("probe", chains=[(prompt, 3)],
                         state=fleet.ReplicaHealth.PROBE)
    views = [_view("cold", queue_depth=5), probe]
    # an unverified half-open replica keeps its last-resort rank,
    # however warm its digest claims it is
    assert fleet.affinity_order(views, {"probe": 3},
                                session_hint="probe") == \
        ["cold", "probe"]
    # a draining/dead/stale warm replica is not in the base order at
    # all: the request proceeds COLD and the plan says why
    gone = _digest_view("gone", chains=[(prompt, 3)], draining=True)
    order, info = fleet.affinity_plan([_view("cold"), gone],
                                      {"gone": 3}, session_hint="gone")
    assert order == ["cold"]
    assert info["hint_routable"] is False


def test_affinity_map_ttl_capacity_and_purge():
    clock = [100.0]
    m = fleet.AffinityMap(capacity=2, ttl_s=5.0, now=lambda: clock[0])
    m.note("s1", "replica-0")
    assert m.lookup("s1") == "replica-0"
    # TTL: an expired entry is evidence-free and self-evicts on read
    clock[0] += 5.1
    assert m.lookup("s1") is None and len(m) == 0
    # capacity is LRU over note recency
    m.note("a", "r0")
    m.note("b", "r1")
    m.note("a", "r0")  # renew: b is now the least recently noted
    m.note("c", "r2")
    assert m.lookup("b") is None
    assert m.lookup("a") == "r0" and m.lookup("c") == "r2"
    # evict reports whether an entry existed (once-per-incident guard)
    assert m.evict("a") is True
    assert m.evict("a") is False
    # purge_replica drops every session pinned to a retiring replica
    m2 = fleet.AffinityMap(capacity=8, ttl_s=5.0, now=lambda: clock[0])
    m2.note("x", "r9")
    m2.note("y", "r9")
    m2.note("z", "r2")
    assert m2.purge_replica("r9") == 2
    assert m2.lookup("x") is None and m2.lookup("z") == "r2"


# -- ReplicaHealth (half-open state machine, injected time) ----------------

def test_health_threshold_then_down_then_probe_then_recover():
    h = fleet.ReplicaHealth(fail_threshold=2, cooldown=10.0)
    assert h.state("r", now=0.0) == h.UP
    h.note_failure("r", now=0.0)
    assert h.state("r", now=0.0) == h.UP, "below threshold stays up"
    h.note_failure("r", now=1.0)
    assert h.state("r", now=1.0) == h.DOWN
    assert h.state("r", now=10.9) == h.DOWN
    # cooldown expired -> half-open
    assert h.state("r", now=11.1) == h.PROBE
    h.note_success("r")
    assert h.state("r", now=11.2) == h.UP


def test_health_probe_failure_redowns_with_escalated_cooldown():
    h = fleet.ReplicaHealth(fail_threshold=1, cooldown=10.0,
                            cooldown_factor=2.0)
    h.note_failure("r", now=0.0)           # down #1: until 10
    assert h.state("r", now=10.5) == h.PROBE
    h.note_failure("r", now=10.5)          # probe failed: down #2 = 20s
    assert h.state("r", now=30.0) == h.DOWN
    assert h.state("r", now=30.6) == h.PROBE


def test_health_success_resets_escalation():
    h = fleet.ReplicaHealth(fail_threshold=1, cooldown=10.0,
                            cooldown_factor=2.0, max_cooldown=100.0)
    h.note_failure("r", now=0.0)
    h.note_failure("r", now=10.5)          # escalated to 20s
    h.note_success("r")                    # verified healthy: full reset
    h.note_failure("r", now=50.0)          # next incident: base cooldown
    assert h.state("r", now=60.5) == h.PROBE


def test_health_cooldown_capped():
    h = fleet.ReplicaHealth(fail_threshold=1, cooldown=10.0,
                            cooldown_factor=10.0, max_cooldown=15.0)
    h.note_failure("r", now=0.0)
    h.note_failure("r", now=10.5)          # would be 100s; capped at 15
    assert h.state("r", now=10.5 + 15.1) == h.PROBE


def test_health_holds_are_owner_scoped():
    """Rolling drain and the supervisor hold a replica independently:
    one releasing must not readmit on the other's behalf (the drain's
    hold stands until ITS wire-verified /healthz)."""
    h = fleet.ReplicaHealth()
    h.quiesce("r", "draining", owner="rolling-drain")
    h.quiesce("r", "engine dead", owner="supervisor")
    h.readmit("r", owner="supervisor")
    assert h.state("r", now=0.0) == h.DOWN
    h.readmit("r", owner="rolling-drain")
    assert h.state("r", now=0.0) == h.UP
    # owner=None is the operator's force-clear
    h.quiesce("r", owner="a")
    h.quiesce("r", owner="b")
    h.readmit("r", owner=None)
    assert h.state("r", now=0.0) == h.UP


def test_health_quiesce_is_administrative_no_probe_path():
    h = fleet.ReplicaHealth(cooldown=0.001)
    h.quiesce("r", "rolling drain")
    assert h.state("r", now=0.0) == h.DOWN
    h.note_success("r")  # traffic outcomes must not override the hold
    assert h.state("r", now=1e9) == h.DOWN, "quiesce never half-opens"
    h.readmit("r")
    assert h.state("r", now=1e9) == h.UP


# -- racecheck regression pins (PR 14): the fleet's shared-state
# fixes, each pinned barrier-style like PR 10's two-thread
# compile-claim test ------------------------------------------------------

def test_new_rid_concurrent_unique():
    """``_next_idx += 1`` was an unlocked read-modify-write shared by
    the autoscaler thread and operator threads: two concurrent
    spawn_replica calls could mint the SAME replica id (two engines,
    one identity, one lease — split-brain by construction). Under the
    fleet lock every id is unique."""
    f = fleet.ServingFleet(None, None, replicas=1)
    n_threads, per_thread = 4, 400
    barrier = threading.Barrier(n_threads)
    out = [None] * n_threads

    def mint(i):
        barrier.wait()
        out[i] = [f._new_rid() for _ in range(per_thread)]

    threads = [threading.Thread(target=mint, args=(i,), daemon=True,
                                name="tfos-test-rid-%d" % i)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    rids = [r for chunk in out for r in chunk]
    assert len(set(rids)) == n_threads * per_thread, \
        "duplicate replica ids minted under concurrency"


def test_replica_lookup_survives_concurrent_churn():
    """``_replica`` used to iterate ``self.replicas`` while spawn /
    retire mutated it from other threads — removing an earlier element
    shifts the list under the iterator and a PRESENT member can be
    skipped (lookup returns None for a replica the fleet tracks).
    Under the lock the anchor is always found."""
    f = fleet.ServingFleet(None, None, replicas=1)

    class _R(object):
        remote = False

        def __init__(self, rid):
            self.replica_id = rid

    churners = [_R("churn-%d" % i) for i in range(8)]
    for r in churners:
        f._track(r)
    anchor = _R("anchor")
    f._track(anchor)
    stop = threading.Event()
    barrier = threading.Barrier(2)
    misses = []

    def churn():
        barrier.wait()
        while not stop.is_set():
            for r in churners:
                f._untrack(r)
            for r in churners:
                f._track(r)

    def lookup():
        barrier.wait()
        for _ in range(3000):
            if f._replica("anchor") is None:
                misses.append(1)
        stop.set()

    ts = [threading.Thread(target=churn, daemon=True,
                           name="tfos-test-churn"),
          threading.Thread(target=lookup, daemon=True,
                           name="tfos-test-lookup")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    stop.set()
    assert not misses, \
        "tracked anchor replica vanished from lookup {} time(s) " \
        "during churn".format(len(misses))


class _FenceServer(object):
    """Minimal ModelServer surface for a bare Replica agent."""

    replica_id = "replica-f"
    engine = None
    name = "model"

    def __init__(self):
        self.fence_reason = None

    def start(self):
        return ("127.0.0.1", 0)

    def fence(self, reason):
        self.fence_reason = reason

    def unfence(self):
        self.fence_reason = None

    def stop(self):
        pass


class _FenceOnceClient(object):
    """reservation.Client stand-in whose FIRST beat parks on a barrier
    (so the test can line a re_register up against the in-flight
    exchange) and then comes back FENCED; every later beat succeeds."""

    barrier = None
    fenced_once = False

    def __init__(self, addr, **kw):  # accepts connect_timeout etc.
        pass

    def lease(self, rid):
        return 1

    def beat(self, rid, payload, epoch=None):
        cls = _FenceOnceClient
        if not cls.fenced_once:
            cls.fenced_once = True
            cls.barrier.wait(timeout=10)
            time.sleep(0.2)  # hold the exchange open past re_register
            raise reservation.Fenced("stale epoch", epoch=2)

    def close(self):
        pass


def test_re_register_never_loses_to_inflight_fence(monkeypatch):
    """Racecheck regression pin: Replica.epoch/fenced were mutated by
    the beat thread AND re_register() with no lock. A re_register
    landing while a FENCED beat was in flight had its reset
    overwritten by the beat's latch — the replica ended permanently
    fenced with a dead beat loop, while re_register reported success.
    Serialized, the latch lands first and re_register then clears it
    and restarts the loop."""
    monkeypatch.setattr(fleet.reservation, "Client", _FenceOnceClient)
    _FenceOnceClient.barrier = threading.Barrier(2)
    _FenceOnceClient.fenced_once = False
    server = _FenceServer()
    replica = fleet.Replica(server, ("127.0.0.1", 1),
                            beat_interval=0.01)
    replica.start()
    try:
        # the first beat is now parked inside its exchange
        _FenceOnceClient.barrier.wait(timeout=10)
        replica.re_register()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and (
                replica.fenced or not replica._thread.is_alive()):
            time.sleep(0.02)
        assert replica.fenced is False, \
            "re_register's reset was overwritten by the in-flight " \
            "fence latch"
        assert server.fence_reason is None, \
            "server left fenced after a successful re_register"
        assert replica._thread.is_alive(), \
            "beat loop dead after re_register"
    finally:
        replica.stop()


def test_concurrent_executor_spawns_pick_distinct_executors(monkeypatch):
    """Review-fix pin: the executor pick (free_executor) and the
    dispatch/track are ONE atomic placement decision. Unserialized,
    two concurrent spawns both read the hosting ledger before either
    tracks its RemoteReplica and both pick the SAME free executor —
    the second bootstrap can never run there. Under the fleet lock
    the second pick sees the first's track and takes the other
    executor."""
    class _FakeResult(object):
        def first_error(self):
            return None

    class _FakeRDD(object):
        def foreachPartitionAsync(self, fn, **kw):
            return _FakeResult()

    class _FakeSC(object):
        def executors_alive(self):
            return ["e0", "e1"]

        def parallelize(self, seq, n):
            return _FakeRDD()

    f = fleet.ServingFleet(None, None, replicas=1,
                           placement="executors", sc=_FakeSC())
    f._started = True
    f._resv_addr = ("127.0.0.1", 0)
    monkeypatch.setattr(
        f, "_await_lease",
        lambda rid, timeout, min_epoch=None: {"addr": ["127.0.0.1", 1]})
    monkeypatch.setattr(fleet.FleetRouter, "_await_healthz",
                        staticmethod(lambda addr, timeout: True))
    barrier = threading.Barrier(2)
    got = [None, None]
    errors = []

    def spawn(i):
        barrier.wait()
        try:
            got[i] = f.spawn_replica(timeout=5)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    ts = [threading.Thread(target=spawn, args=(i,), daemon=True,
                           name="tfos-test-spawn-%d" % i)
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errors, errors
    eids = {r.executor_id for r in got if r is not None}
    assert eids == {"e0", "e1"}, \
        "concurrent spawns double-placed: {}".format(eids)


def _fake_executor_fleet(monkeypatch, executors):
    class _FakeResult(object):
        def first_error(self):
            return None

    class _FakeRDD(object):
        def foreachPartitionAsync(self, fn, **kw):
            return _FakeResult()

    class _FakeSC(object):
        def executors_alive(self):
            return list(executors)

        def parallelize(self, seq, n):
            return _FakeRDD()

    f = fleet.ServingFleet(None, None, replicas=1,
                           placement="executors", sc=_FakeSC())
    f._started = True
    f._resv_addr = ("127.0.0.1", 0)
    monkeypatch.setattr(
        f, "_await_lease",
        lambda rid, timeout, min_epoch=None: {"addr": ["127.0.0.1", 1]})
    monkeypatch.setattr(fleet.FleetRouter, "_await_healthz",
                        staticmethod(lambda addr, timeout: True))
    return f


def test_replacement_can_reuse_the_corpses_own_executor(monkeypatch):
    """Review-fix pin: the executor pick used to run while the corpse
    handle was still tracked, so the victim's own executor read as
    hosting and was excluded — on a single-executor fleet every
    replacement raised NoCapacity forever even after the executor
    revived. The corpse is untracked before the pick now."""
    f = _fake_executor_fleet(monkeypatch, ["e0"])
    corpse = fleet.RemoteReplica("replica-0", f.reservation,
                                 executor_id="e0")
    f._track(corpse)
    replacement = f.spawn_replica(replica_id="replica-0", timeout=5)
    assert replacement.executor_id == "e0"
    assert f._replica("replica-0") is replacement

    # and a replacement that finds NO capacity keeps the dead
    # identity TRACKED (the PR-13 contract: REPLACE must re-fire)
    f2 = _fake_executor_fleet(monkeypatch, [])
    corpse2 = fleet.RemoteReplica("replica-9", f2.reservation,
                                  executor_id="gone")
    f2._track(corpse2)
    with pytest.raises(fleet.NoCapacity):
        f2.spawn_replica(replica_id="replica-9", timeout=5)
    assert f2._replica("replica-9") is corpse2, \
        "NoCapacity untracked the corpse — the autoscaler would " \
        "forget the dead identity"


# -- replica identity schema (satellite) -----------------------------------

def test_replica_id_stable_across_respawn(lm):
    dec, params = lm
    eng = serving.DecodeEngine(dec, params, slots=1,
                               replica_id="replica-x")
    try:
        assert eng.replica_id == "replica-x"
        assert eng.load_stats()["replica_id"] == "replica-x"
        eng.stop()
        fresh = eng.respawn()
        try:
            assert fresh.replica_id == "replica-x", \
                "replica identity must survive respawn()"
        finally:
            fresh.stop()
    finally:
        eng.stop()


def test_default_replica_ids_are_distinct(lm):
    dec, params = lm
    a = serving.DecodeEngine(dec, params, slots=1)
    b = serving.DecodeEngine(dec, params, slots=1)
    try:
        assert a.replica_id and b.replica_id
        assert a.replica_id != b.replica_id
    finally:
        a.stop()
        b.stop()


def test_healthz_and_metrics_carry_replica_id(lm):
    """Pinned schema: /healthz body has ``replica_id``; /metrics has the
    ``tfos_serving_replica_info{replica_id=...} 1`` join gauge."""
    dec, params = lm
    eng = serving.DecodeEngine(dec, params, slots=1,
                               replica_id="replica-7")
    server = serving.ModelServer(None, engine=eng, name="m", port=0)
    host, port = server.start()
    try:
        _, body = _get("http://%s:%d/healthz" % (host, port))
        assert json.loads(body)["replica_id"] == "replica-7"
        _, text = _get("http://%s:%d/metrics" % (host, port))
        assert '# TYPE tfos_serving_replica_info gauge' in text
        assert 'tfos_serving_replica_info{replica_id="replica-7"} 1' \
            in text
        assert text.endswith("# EOF\n")
    finally:
        server.stop()


def test_engine_failed_503_carries_kind(lm):
    """Pinned schema: a retriable 503's body names WHICH transient
    condition (``kind``) — the router penalizes EngineFailed but not
    Shed/Draining, and it can only tell them apart through this."""
    dec, params = lm
    eng = serving.DecodeEngine(dec, params, slots=1)
    server = serving.ModelServer(None, engine=eng, name="m", port=0)
    host, port = server.start()
    try:
        eng._broken = RuntimeError("boom")  # engine failed, server up
        with pytest.raises(urllib.error.HTTPError) as err:
            _post("http://%s:%d/v1/models/m:generate" % (host, port),
                  {"prompt": [1, 2], "max_new_tokens": 2})
        assert err.value.code == 503
        body = json.loads(err.value.read())
        assert body["kind"] == "EngineFailed"
        assert err.value.headers.get("Retry-After") is not None
    finally:
        eng._broken = None
        server.stop()


# -- reservation serving-role lease view (satellite) -----------------------

def test_reservation_serving_snapshot_and_stats_view():
    server = reservation.Server(0)
    addr = server.start(host="127.0.0.1")
    client = reservation.Client(addr)
    try:
        # a trainer-style lease must NOT appear in the serving view
        client.beat(0, {"state": "running", "train_step": 3})
        client.beat("replica-0", {
            "role": "serving", "replica_id": "replica-0",
            "addr": ["127.0.0.1", 1234], "model": "lm",
            "serving": {"queue_depth": 2, "slot_occupancy": 1,
                        "queue_wait_ewma_s": 0.05, "alive": True,
                        "draining": False}})
        snap = server.serving_snapshot()
        assert set(snap) == {"replica-0"}
        view = snap["replica-0"]
        assert view["addr"] == ["127.0.0.1", 1234]
        assert view["model"] == "lm"
        assert view["serving"]["queue_depth"] == 2
        assert view["age"] < 5.0
        # /stats exposes the same view under the "serving" key
        assert server.stats_addr is not None
        _, body = _get("http://%s:%d/stats" % tuple(server.stats_addr))
        stats = json.loads(body)
        assert set(stats["serving"]) == {"replica-0"}
        assert stats["serving"]["replica-0"]["serving"][
            "slot_occupancy"] == 1
        assert "metrics" not in stats["serving"]["replica-0"]
    finally:
        client.close()
        server.stop()


# -- fleet e2e (tier-1: small, fast) ---------------------------------------

def test_two_replica_fleet_routes_and_matches_solo(lm):
    """The core fleet contract over real HTTP: concurrent requests
    through the router all succeed, every output is bitwise-identical
    to a solo generate, the router's /healthz sees both replicas, and
    /metrics exposes per-replica labeled serving series plus the
    fleet families."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2}) as f:
        url = f.url("/v1/models/lm:generate")
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10, 2], [3, 3, 3]]
        results = [None] * len(prompts)

        def client(i):
            status, body = _post(url, {"prompt": prompts[i],
                                       "max_new_tokens": 6})
            results[i] = (status, body["tokens"])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, prompt in enumerate(prompts):
            status, tokens = results[i]
            assert status == 200
            assert tokens == _solo(dec, params, prompt, 6)
        status, body = _get(f.url("/healthz"))
        health = json.loads(body)
        assert status == 200 and health["routable"] == 2
        assert set(health["replicas"]) == {"replica-0", "replica-1"}
        _, text = _get(f.url("/metrics"))
        assert text.endswith("# EOF\n")
        assert "tfos_fleet_requests_total" in text
        assert 'tfos_fleet_replica_up{replica="replica-0"} 1' in text
        assert 'tfos_fleet_replica_up{replica="replica-1"} 1' in text
        # per-replica labeled engine series from the beat snapshots
        assert 'replica="replica-0"' in text \
            and "tfos_serving_decode_steps_total" in text
        counts = f.router.counters.snapshot()["counts"]
        assert counts.get("requests") == len(prompts)
        assert counts.get("failovers", 0) == 0


def test_router_404_and_healthz_unavailable_when_no_replicas():
    resv = reservation.Server(0)
    resv.start(host="127.0.0.1")
    router = fleet.FleetRouter(resv, name="lm")
    try:
        host, port = router.start()
        # healthz: 503 with routable == 0 (no leases at all)
        with pytest.raises(urllib.error.HTTPError) as err:
            _get("http://%s:%d/healthz" % (host, port))
        assert err.value.code == 503
        assert json.loads(err.value.read())["routable"] == 0
        # unknown route -> 404
        with pytest.raises(urllib.error.HTTPError) as err:
            _get("http://%s:%d/nope" % (host, port))
        assert err.value.code == 404
        # a generate with nothing routable -> retriable 503 with
        # Retry-After after the bounded failover budget
        with pytest.raises(urllib.error.HTTPError) as err:
            _post("http://%s:%d/v1/models/lm:generate" % (host, port),
                  {"prompt": [1], "max_new_tokens": 1})
        assert err.value.code == 503
        assert err.value.headers.get("Retry-After") is not None
        assert json.loads(err.value.read())["kind"] == \
            "NoReplicaAvailable"
    finally:
        router.stop()
        resv.stop()


def test_draining_replica_excluded_by_its_own_beat(lm):
    """A replica whose engine is draining advertises it on its next
    beat and the router stops routing to it — no health penalty, no
    failover storm, just exclusion."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        victim = f.replicas[0].engine
        victim.drain()  # drains idle engine; draining+stopped flags set
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            views = f.router.replica_views()
            order = fleet.route_order(views, f.router.stale_after)
            if order == ["replica-1"]:
                break
            time.sleep(0.05)
        assert fleet.route_order(
            f.router.replica_views(), f.router.stale_after) == \
            ["replica-1"]
        # traffic still flows, all of it to the survivor
        status, body = _post(f.url("/v1/models/lm:generate"),
                             {"prompt": [1, 2], "max_new_tokens": 3})
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [1, 2], 3)


def test_client_disconnect_propagates_through_router(lm):
    """The PR-4 disconnect contract survives the extra hop: when the
    router's OWN client hangs up mid-request, the router tears down
    its upstream connection, the replica's socket-EOF cancel fires,
    and the slot frees instead of decoding to max_new for nobody."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=1, name="lm",
                            engine_kw={"slots": 1},
                            beat_interval=0.05) as f:
        engine = f.replicas[0].engine
        # warm the programs, then hold the next request's first step
        # boundary open so the disconnect provably lands mid-flight
        _post(f.url("/v1/models/lm:generate"),
              {"prompt": [1, 2], "max_new_tokens": 2})
        chaos.arm("stall_decode_for=1.5")
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_new_tokens": 40}).encode()
        host, port = f.router_addr
        sock = socket.create_connection((host, port), timeout=30)
        sock.sendall(
            b"POST /v1/models/lm:generate HTTP/1.1\r\n"
            b"Host: x\r\nContent-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() +
            b"\r\n\r\n" + body)
        # wait until the request is admitted upstream, then vanish
        assert chaos.poll_until(
            lambda: _counts(engine).get("prefills", 0) >= 2, timeout=60)
        sock.close()
        # the victim's slot frees at the next step boundary: cancelled
        # counter ticks and occupancy returns to 0 long before a
        # 40-token rollout could finish
        assert chaos.poll_until(
            lambda: _counts(engine).get("cancelled", 0) >= 1, timeout=30)
        assert chaos.poll_until(
            lambda: engine.counters.snapshot()["gauges"]
            .get("slot_occupancy") == 0, timeout=30)
        assert chaos.poll_until(
            lambda: f.router.counters.snapshot()["counts"]
            .get("client_disconnects", 0) >= 1, timeout=10)
        chaos.disarm()
        # the replica is NOT penalized: the next request routes fine
        status, rbody = _post(f.url("/v1/models/lm:generate"),
                              {"prompt": [1, 2], "max_new_tokens": 3})
        assert status == 200
        assert rbody["tokens"] == _solo(dec, params, [1, 2], 3)


# -- rolling drain (weight-upgrade cycle, live traffic) --------------------

@pytest.mark.slow
def test_rolling_drain_zero_lost_requests_under_traffic(lm):
    """The acceptance pin: ``rolling_drain()`` across 3 replicas
    completes a weight-upgrade cycle — every replica's engine replaced
    (fresh object, same identity), zero lost requests among continuous
    client traffic, zero drain loss. The upgrade callable swaps in a
    second params object, standing in for new weights."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=3, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        url = f.url("/v1/models/lm:generate")
        old_engines = {r.replica_id: r.engine for r in f.replicas}
        stop = threading.Event()
        failures, successes = [], []

        def traffic():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    status, body = _post(
                        url, {"prompt": [1 + i % 5, 2],
                              "max_new_tokens": 4})
                    assert status == 200
                    successes.append(body["tokens"])
                except Exception as e:  # noqa: BLE001 - the assertion
                    failures.append(repr(e))
                time.sleep(0.01)

        threads = [threading.Thread(target=traffic) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.3)  # traffic flowing before the cycle starts

            def upgrade(old):
                return serving.DecodeEngine(
                    dec, params, slots=2, replica_id=old.replica_id)

            report = f.rolling_drain(upgrade=upgrade,
                                     healthz_timeout=30.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert report["completed"] and report["zero_loss"], report
        assert [r["replica_id"] for r in report["replicas"]] == \
            ["replica-0", "replica-1", "replica-2"]
        assert all(r["drained_clean"] and r["recovered"]
                   for r in report["replicas"]), report
        # every engine object was replaced; identity survived
        for replica in f.replicas:
            assert replica.engine is not old_engines[replica.replica_id]
            assert replica.engine.replica_id == replica.replica_id
        assert not failures, failures
        assert successes, "traffic must have flowed during the cycle"
        # outputs stayed solo-correct through the swaps
        want = {tuple(_solo(dec, params, [1 + i, 2], 4))
                for i in range(5)}
        assert {tuple(t) for t in successes} <= want


# -- chaos: kill one replica mid-stream (collected by `make chaos`) --------

@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_kill_one_replica_zero_client_visible_failures(lm):
    """The fleet acceptance e2e: 3 replicas behind the router, chaos
    kills ONE replica's decode scheduler mid-stream
    (``kill_scheduler_at_step`` scoped by ``only=<replica_id>``).
    Every in-flight and subsequent client request completes with the
    bitwise solo output (failures stay INTERNAL: retriable 503s the
    router fails over); the supervisor quiesces the replica first,
    restarts its engine, readmits it; MTTR is recorded from the event
    log."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=3, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        f.supervise()
        url = f.url("/v1/models/lm:generate")
        # warm the shared decode programs so the kill lands mid-decode,
        # not mid-compile
        _post(url, {"prompt": [1, 2, 3], "max_new_tokens": 2})
        chaos.arm("kill_scheduler_at_step=3,only=replica-1")
        results, errors = [], []

        def client(i):
            try:
                status, body = _post(
                    url, {"prompt": [1 + i % 5, 2, 3],
                          "max_new_tokens": 16}, timeout=180)
                results.append((i, status, body["tokens"]))
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, \
            "client-visible failures during replica kill: %s" % errors
        assert len(results) == 12
        for i, status, tokens in results:
            assert status == 200
            assert tokens == _solo(dec, params, [1 + i % 5, 2, 3], 16)
        # the kill actually happened and was failed over internally
        assert chaos.poll_until(
            lambda: any(e["name"] == "engine_restarted"
                        for e in f.supervisor.events.events()),
            timeout=60), "supervised restart never completed"
        events = f.supervisor.events.events()
        dead = [e for e in events if e["name"] == "engine_dead"]
        restarted = [e for e in events if e["name"] == "engine_restarted"]
        assert dead and restarted
        assert dead[0].get("replica") == "replica-1"
        mttr = restarted[0]["t"] - dead[0]["t"]
        assert 0 <= mttr < 60, mttr
        # restart counted on the shared counters (series continuity)
        assert f.replicas[1].engine.counters.snapshot()["counts"] \
            .get("engine_restarts") == 1
        # the revived replica serves again (readmitted): wait until the
        # router would route to it, then push one more request through
        assert chaos.poll_until(
            lambda: "replica-1" in fleet.route_order(
                f.router.replica_views(), f.router.stale_after),
            timeout=30), "killed replica never readmitted"
        status, body = _post(url, {"prompt": [9, 2, 3],
                                   "max_new_tokens": 4})
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [9, 2, 3], 4)


def test_fleet_stop_then_start_reforms(lm):
    """stop() fully resets fleet state: a second start() re-forms with
    fresh replicas and a fresh reservation server instead of routing,
    draining, or watching over stopped corpses."""
    dec, params = lm
    f = fleet.ServingFleet(dec, params, replicas=1, name="lm",
                           engine_kw={"slots": 1})
    f.start()
    f.stop()
    assert f.replicas == [] and f.router is None
    f.start()
    try:
        assert len(f.replicas) == 1
        status, body = _post(f.url("/v1/models/lm:generate"),
                             {"prompt": [5, 1], "max_new_tokens": 3})
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [5, 1], 3)
    finally:
        f.stop()


def test_cluster_serving_fleet_helper(lm):
    """cluster.serving_fleet: one call forms, starts, and (optionally)
    supervises an in-process fleet."""
    dec, params = lm
    f = cluster.serving_fleet(dec, params, replicas=2, name="lm",
                              engine_kw={"slots": 1}, supervise=True)
    try:
        assert f.supervisor is not None
        assert len(f.supervisor._watched) == 2
        status, body = _post(f.url("/v1/models/lm:generate"),
                             {"prompt": [2, 4], "max_new_tokens": 3})
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [2, 4], 3)
    finally:
        f.stop()


# -- trace-context propagation (PR 10): X-TFOS-Trace + /debug/trace --------

def _get_with_headers(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode(), dict(r.headers)


def _stitched_sources(doc):
    """{label: set of tids with any event} from a stitched document."""
    labels = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    out = {label: set() for label in labels.values()}
    for e in doc["traceEvents"]:
        if e.get("ph") in ("X", "i"):
            out[labels[e["pid"]]].add(e["tid"])
    return out


def test_router_mints_trace_and_debug_trace_stitches_replica(lm):
    """One routed request: the router mints an X-TFOS-Trace id, the
    replica engine ADOPTS it, and GET /debug/trace on the router
    returns ONE stitched Perfetto document where the router's dispatch
    span and the replica's engine spans share that id — with the ring
    saturation total in the X-TFOS-Trace-Dropped header."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=1, name="lm",
                            engine_kw={"slots": 1},
                            beat_interval=0.05) as f:
        # ServingFleet gives each replica its OWN ring (one ring per
        # process in real deployments) — pinned here: the stitch labels
        # spans by source, which a shared global ring would make vacuous
        assert f.replicas[0].engine.flight \
            is not fleet.tracing.flight_recorder()
        status, body = _post(f.url("/v1/models/lm:generate"),
                             {"prompt": [3, 1, 4], "max_new_tokens": 3})
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [3, 1, 4], 3)
        status, text, headers = _get_with_headers(f.url("/debug/trace"))
        assert status == 200
        assert "X-TFOS-Trace-Dropped" in headers
        assert int(headers["X-TFOS-Trace-Dropped"]) >= 0
        doc = json.loads(text)
        assert doc.get("dropped", {}).keys() == {"router", "replica-0"}
        dispatches = [e for e in doc["traceEvents"]
                      if e.get("name") == "dispatch"
                      and e.get("ph") == "X"]
        assert len(dispatches) == 1
        trace_id = dispatches[0]["tid"]
        assert dispatches[0]["args"]["status"] == 200
        sources = _stitched_sources(doc)
        # the minted id joins the router's row to the replica's spans
        assert trace_id in sources["router"]
        assert trace_id in sources["replica-0"], sources
        # the replica actually emitted engine lifecycle spans under it
        replica_spans = {e["name"] for e in doc["traceEvents"]
                         if e.get("ph") == "X"
                         and e["tid"] == trace_id
                         and e.get("name") != "dispatch"
                         and e.get("name") != "upstream"}
        assert {"prefill", "decode"} <= replica_spans, replica_spans


@pytest.mark.slow
@pytest.mark.chaos
def test_failover_request_yields_one_stitched_cross_replica_trace(
        lm, tmp_path):
    """Acceptance (PR 10): a fleet request that fails over MID-STREAM
    produces one stitched trace containing spans from BOTH replicas —
    the dying replica's partial lifecycle and the survivor's complete
    one share the single router-minted trace id."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        assert f.replicas[0].engine.flight \
            is not f.replicas[1].engine.flight, \
            "fleet replicas must own distinct span rings"
        url = f.url("/v1/models/lm:generate")
        # UNSCOPED kill + fuse: the decode-step site only fires on an
        # engine with ACTIVE slots, so the victim is deterministically
        # whichever replica serves the request — and the single-shot
        # fuse guarantees the survivor completes the failover
        chaos.arm("kill_scheduler_at_step=5,fuse={}".format(
            tmp_path / "kill_fuse"))
        status, body = _post(url, {"prompt": [2, 3, 4],
                                   "max_new_tokens": 16}, timeout=180)
        # the client saw ONE clean answer (the failover is internal)
        assert status == 200
        assert body["tokens"] == _solo(dec, params, [2, 3, 4], 16)
        status, text, headers = _get_with_headers(f.url("/debug/trace"))
        assert status == 200
        doc = json.loads(text)
        # the failed-over dispatch: >1 upstream attempt on one trace id
        dispatches = [e for e in doc["traceEvents"]
                      if e.get("name") == "dispatch"
                      and e.get("ph") == "X"
                      and e["args"].get("attempts", 1) > 1]
        assert dispatches, "no failed-over dispatch recorded"
        trace_id = dispatches[0]["tid"]
        sources = _stitched_sources(doc)
        assert trace_id in sources["replica-0"], sources
        assert trace_id in sources["replica-1"], sources
        # one upstream span per attempt, both on the request's row
        upstreams = [e for e in doc["traceEvents"]
                     if e.get("name") == "upstream"
                     and e["tid"] == trace_id]
        assert len(upstreams) == 2
        assert {u["args"]["replica"] for u in upstreams} == \
            {"replica-0", "replica-1"}


# -- prefix/session affinity (PR 16; e2e + chaos) --------------------------


def test_session_affinity_sticky_routing_and_schema(lm):
    """A conversation carrying a ``session`` id sticks to the replica
    that served its first turn (the dispatch-history side of the
    affinity map — no digest needed), turn-2 stays bitwise-solo, and
    the new observability schema renders: affinity counters on the
    router, digest gauges per replica."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        url = f.url("/v1/models/lm:generate")
        p1 = list(range(1, 14))
        status, body = _post(url, {"prompt": p1, "max_new_tokens": 8,
                                   "session": "conv-1"})
        assert status == 200
        t1 = body["tokens"]
        rid = f.router.affinity.lookup("conv-1")
        assert rid in ("replica-0", "replica-1")
        # turn 2: continuation of turn 1 under the same session id
        p2 = t1 + [3]
        want = _solo(dec, params, p2, 6)
        for _ in range(3):
            status, body = _post(url, {"prompt": p2,
                                       "max_new_tokens": 6,
                                       "session": "conv-1"})
            assert status == 200 and body["tokens"] == want
            assert f.router.affinity.lookup("conv-1") == rid
        counts = f.router.counters.snapshot()["counts"]
        assert counts.get("affinity_hits", 0) >= 3
        # a sessionless request neither reads nor grows the map
        status, _ = _post(url, {"prompt": [5, 6], "max_new_tokens": 2})
        assert status == 200 and len(f.router.affinity) == 1
        status, body = _get(f.url("/healthz"))
        health = json.loads(body)
        assert health["affinity_entries"] == 1
        assert all("prefix_digest_chains" in v
                   for v in health["replicas"].values())
        _, text = _get(f.url("/metrics"))
        assert "tfos_fleet_affinity_entries 1" in text
        assert "tfos_serving_prefix_digest_chains" in text
        # session type errors are the replica's 400, not a router crash
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(url, {"prompt": [1, 2], "max_new_tokens": 2,
                        "session": 7})
        assert err.value.code == 400


@pytest.mark.slow
@pytest.mark.chaos
def test_affinity_kill_warm_replica_fails_over_cold(lm):
    """The PR 16 failover contract, end to end: a conversation's warm
    replica is killed mid-session; the next turn completes 200 served
    COLD with bitwise solo-identical tokens at temp=0, zero duplicate
    completions, and the affinity map entry for the dead replica is
    evicted (counted as ``affinity_breaks{failover_cold}``) before
    the session rebinds to its new home."""
    dec, params = lm
    with fleet.ServingFleet(dec, params, replicas=3, name="lm",
                            engine_kw={"slots": 2},
                            beat_interval=0.05) as f:
        f.supervise()
        url = f.url("/v1/models/lm:generate")
        # warm the shared decode programs (sessionless: no map entry)
        _post(url, {"prompt": [1, 2, 3], "max_new_tokens": 2})
        p1 = list(range(1, 14))
        status, body = _post(url, {"prompt": p1, "max_new_tokens": 8,
                                   "session": "conv"})
        assert status == 200
        t1 = body["tokens"]
        warm_rid = f.router.affinity.lookup("conv")
        assert warm_rid is not None
        # kill the WARM replica's scheduler on its next decode steps
        chaos.arm("kill_scheduler_at_step=3,only={}".format(warm_rid))
        p2 = t1 + [3]
        status, body = _post(url, {"prompt": p2, "max_new_tokens": 16,
                                   "session": "conv"}, timeout=180)
        assert status == 200
        assert body["tokens"] == _solo(dec, params, p2, 16)
        # served COLD: the session moved off the dead replica, through
        # an explicit eviction (failover_cold), then rebound
        new_rid = f.router.affinity.lookup("conv")
        assert new_rid is not None and new_rid != warm_rid
        with f.router._obs_lock:
            breaks = dict(f.router._affinity_breaks)
        assert breaks.get("failover_cold", 0) >= 1
        # zero duplicate completions: every client request completed
        # exactly once across the whole fleet (the dead replica's
        # aborted attempt never produced a second completion)
        total = sum(r.engine.counters.snapshot()["counts"]
                    .get("requests_completed", 0) for r in f.replicas)
        assert total == 3
        # the killed replica recovers under supervision and can be
        # routed again — affinity healing is just future dispatches
        assert chaos.poll_until(
            lambda: warm_rid in fleet.route_order(
                f.router.replica_views(), f.router.stale_after),
            timeout=60), "killed replica never readmitted"
