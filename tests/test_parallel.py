"""Advanced-parallelism tests on the virtual 8-device CPU mesh: ring
attention vs full-attention oracle, tensor-parallel sharding rules,
pipeline parallelism, and expert-parallel MoE."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax():
    import jax
    return jax


def test_ring_attention_matches_reference(jax):
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = build_mesh({"seq": 8})
    B, S, N, D = 2, 64, 4, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)

    for causal in (False, True):
        want = reference_attention(q, k, v, causal=causal)
        got = jax.jit(
            lambda q, k, v, c=causal: ring_attention(q, k, v, mesh,
                                                     causal=c))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_ring_attention_sharded_inputs(jax):
    """With properly sharded inputs the output keeps the seq sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import ring_attention

    mesh = build_mesh({"seq": 8})
    sharding = NamedSharding(mesh, P(None, "seq", None, None))
    B, S, N, D = 1, 32, 2, 8
    x = jax.device_put(np.ones((B, S, N, D), np.float32), sharding)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(x, x, x)
    assert out.shape == (B, S, N, D)
    assert out.sharding.spec == P(None, "seq", None, None)


def test_tp_sharding_rules(jax):
    from tensorflowonspark_tpu.models import bert
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.sharding import (
        BERT_TP_RULES, param_path_specs, tree_shardings)

    cfg = bert.bert_tiny()
    model = bert.BertForQuestionAnswering(cfg)
    ids = np.zeros((2, 16), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    specs = param_path_specs(params, BERT_TP_RULES)
    ffn_in = [s for name, s in specs.items() if "ffn_in/kernel" in name]
    assert ffn_in
    assert all(tuple(s) == (None, "model") for s in ffn_in)
    ffn_out = [s for name, s in specs.items() if "ffn_out/kernel" in name]
    assert all(tuple(s) == ("model", None) for s in ffn_out)
    ln = [s for name, s in specs.items() if "ln_attn" in name]
    assert all(tuple(s) == () for s in ln)  # replicated

    mesh = build_mesh({"data": 4, "model": 2})
    shardings = tree_shardings(params, mesh, BERT_TP_RULES)
    sharded = jax.device_put(params, shardings)
    # a TP matmul against sharded params must produce the right numbers
    leaf = sharded["bert"]["layer_0"]["ffn_in"]["kernel"]
    assert len(leaf.sharding.device_set) == 8


def test_tp_forward_matches_replicated(jax):
    """BERT forward with TP-sharded params == replicated params."""
    from tensorflowonspark_tpu.models import bert
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.sharding import (
        BERT_TP_RULES, tree_shardings)

    cfg = bert.bert_tiny()
    model = bert.BertForSequenceClassification(cfg, num_classes=3)
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
    variables = model.init(jax.random.PRNGKey(0), ids)
    want = model.apply(variables, ids)

    mesh = build_mesh({"data": 4, "model": 2})
    shardings = {"params": tree_shardings(variables["params"], mesh,
                                          BERT_TP_RULES)}
    sharded_vars = jax.device_put(variables, shardings)
    got = jax.jit(model.apply)(sharded_vars, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)  # bf16 reassociation


def test_pipeline_apply(jax):
    import jax.numpy as jnp

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.pipeline import (
        pipeline_apply, stack_stage_params)

    mesh = build_mesh({"stage": 4}, devices=jax.devices()[:4])
    P_stages, M, mb, width = 4, 6, 8, 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def init_fn(rng, sample_x):
        return {"w": jax.random.normal(rng, (width, width)) * 0.3,
                "b": jnp.zeros((width,))}

    rng = jax.random.PRNGKey(0)
    stage_params = stack_stage_params(init_fn, rng, P_stages,
                                      np.zeros((mb, width)))
    xs = np.random.RandomState(0).randn(M, mb, width).astype(np.float32)

    got = jax.jit(lambda p, x: pipeline_apply(stage_fn, p, x, mesh))(
        stage_params, xs)

    # oracle: apply the 4 stages sequentially to each microbatch
    want = xs
    for s in range(P_stages):
        p_s = jax.tree.map(lambda leaf: leaf[s], stage_params)
        want = np.stack([np.asarray(stage_fn(p_s, want[m]))
                         for m in range(M)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_moe_expert_parallel(jax):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.moe import (
        init_moe_params, moe_ffn, top1_gating)

    mesh = build_mesh({"expert": 8})
    T, H, F, E = 32, 16, 32, 8
    router_w, w_in, w_out = init_moe_params(jax.random.PRNGKey(0), E, H, F)
    w_in = jax.device_put(w_in, NamedSharding(mesh, P("expert")))
    w_out = jax.device_put(w_out, NamedSharding(mesh, P("expert")))
    x = np.random.RandomState(0).randn(T, H).astype(np.float32)

    y, aux = jax.jit(
        lambda x, r, wi, wo: moe_ffn(x, r, wi, wo, mesh))(
        x, router_w, w_in, w_out)
    assert y.shape == (T, H)
    assert float(aux) > 0

    # oracle: dense single-device computation of the same routing
    logits = x @ np.asarray(router_w)
    one_hot, gate, _ = top1_gating(logits)
    h = np.einsum("th,ehf->etf", x, np.asarray(w_in))
    h = np.asarray(jax.nn.gelu(h))
    y_all = np.einsum("etf,efh->eth", h, np.asarray(w_out))
    want = np.einsum("eth,te->th", y_all,
                     np.asarray(one_hot) * np.asarray(gate)[:, None])
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)


def test_ring_flash_attention_matches_reference(jax):
    """Ring schedule with the Pallas flash kernel (interpret mode) as
    the block engine: forward parity against the full-attention oracle,
    both causal modes."""
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention, ring_flash_attention)

    import jax as _jax
    mesh = build_mesh({"seq": 4}, devices=_jax.devices()[:4])
    B, S, N, D = 1, 64, 2, 16  # s_local = 16
    rng = np.random.RandomState(3)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)

    for causal in (False, True):
        want = reference_attention(q, k, v, causal=causal)
        got = jax.jit(
            lambda q, k, v, c=causal: ring_flash_attention(
                q, k, v, mesh, causal=c, block_q=16, block_k=16,
                interpret=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_ring_flash_attention_grads_match_reference(jax):
    """Gradients through the ring merge AND the kernel's (out, lse) vjp
    (the g_lse -> delta fold) against oracle grads."""
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention, ring_flash_attention)

    import jax as _jax
    mesh = build_mesh({"seq": 4}, devices=_jax.devices()[:4])
    B, S, N, D = 1, 32, 2, 8  # s_local = 8
    rng = np.random.RandomState(4)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)
    w = rng.randn(B, S, N, D).astype(np.float32)

    for causal in (False, True):
        def loss_ring(q, k, v, c=causal):
            out = ring_flash_attention(q, k, v, mesh, causal=c,
                                       block_q=8, block_k=8,
                                       interpret=True)
            return (w * out).sum()

        def loss_ref(q, k, v, c=causal):
            return (w * reference_attention(q, k, v, causal=c)).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gr, gw in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gw),
                                       rtol=2e-3, atol=2e-3)


def test_flash_attention_lse_merge_identity(jax):
    """Two disjoint-KV partials merged == attention over the union."""
    from tensorflowonspark_tpu.ops.flash_attention import (
        flash_attention_lse)
    from tensorflowonspark_tpu.parallel.ring_attention import (
        _merge_partials, reference_attention)

    B, S, N, D = 1, 32, 2, 8
    rng = np.random.RandomState(5)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)

    o1, l1 = flash_attention_lse(q, k[:, :16], v[:, :16], block_q=8,
                                 block_k=8, interpret=True)
    o2, l2 = flash_attention_lse(q, k[:, 16:], v[:, 16:], block_q=8,
                                 block_k=8, interpret=True)
    merged, _ = _merge_partials(o1.astype(np.float32), l1,
                                o2.astype(np.float32), l2)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_longcontext_example_learns(jax):
    """examples/longcontext: causal LM over ring+flash on a seq mesh
    learns a periodic task that REQUIRES long-range attention."""
    import sys

    sys.path.insert(0, "/root/repo")
    from examples.longcontext import long_dist

    first, last = long_dist.train(
        seq_len=256, batch=2, steps=15, hidden=32, heads=2, layers=1,
        period=13, seq_devices=4, interpret=True, log_every=0)
    assert last < first * 0.7, (first, last)


def test_pipeline_apply_is_differentiable(jax):
    """PP training: grads through the ppermute microbatch schedule match
    running the stages sequentially (reverse-mode over the fori_loop +
    collective-permute transpose)."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.pipeline import (
        pipeline_apply, stack_stage_params)

    mesh = build_mesh({"stage": 4}, devices=jax.devices()[:4])
    H = 8

    def stage_init(r, x):
        return {"w": jax.random.normal(r, (H, H)) * 0.4}

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    rng = np.random.RandomState(9)
    M, mb = 6, 3
    xs = rng.randn(M, mb, H).astype(np.float32)
    tgt = rng.randn(M, mb, H).astype(np.float32)
    sp = stack_stage_params(stage_init, jax.random.PRNGKey(7), 4, xs[0])

    def loss_pp(p):
        out = pipeline_apply(stage_fn, p, xs, mesh)
        return jnp.mean((out - tgt) ** 2)

    def loss_seq(p):
        out = xs
        for i in range(4):
            out = stage_fn(jax.tree.map(lambda w: w[i], p), out)
        return jnp.mean((out - tgt) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(sp)
    g_seq = jax.grad(loss_seq)(sp)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_widedeep_sharded_embedding_training_step(jax):
    """Config #4 story: Wide&Deep with its embedding TABLES row-sharded
    over the model axis — one DP x TP training step, finite loss, live
    gradients into the sharded tables."""
    import optax

    from tensorflowonspark_tpu import training
    from tensorflowonspark_tpu.models.widedeep import WideDeep, ctr_loss
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.sharding import (
        WIDEDEEP_TP_RULES, tree_shardings)

    mesh = build_mesh({"data": 2, "model": 2}, devices=jax.devices()[:4])
    model = WideDeep(num_dense=4, num_cat=6, hash_buckets=64, embed_dim=8,
                     mlp_sizes=(16, 16))
    rng = np.random.RandomState(0)
    B = 8
    batch = {
        "dense": rng.rand(B, 4).astype(np.float32),
        "cat": rng.randint(0, 64, size=(B, 6)).astype(np.int32),
        "label": (np.arange(B) % 2).astype(np.float32),
    }
    trainer = training.Trainer(
        model, optax.adagrad(0.05), mesh, loss_fn=ctr_loss,
        input_keys=("dense", "cat"), constrain_state=False)
    state = trainer.init(jax.random.PRNGKey(0), batch)
    shardings = tree_shardings(state["params"], mesh, WIDEDEEP_TP_RULES)
    state["params"] = jax.device_put(state["params"], shardings)

    before = np.asarray(
        state["params"]["deep_embeddings"]["embedding"], np.float32).copy()
    state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    after = np.asarray(
        state["params"]["deep_embeddings"]["embedding"], np.float32)
    assert not np.allclose(before, after)  # sharded table actually trains
    # the table layout survived the step (constrain_state=False contract)
    spec = state["params"]["deep_embeddings"]["embedding"] \
        .sharding.spec
    assert tuple(spec)[0] == "model", spec


def test_build_hybrid_mesh_layout(jax):
    """DCN axes outer, ICI axes inner: each inner block is a contiguous
    run of the global device order (slice-major, matching jax.devices()'s
    process-major ordering), so model/seq collectives stay intra-slice."""
    import numpy as np

    from tensorflowonspark_tpu.parallel import build_hybrid_mesh

    mesh = build_hybrid_mesh({"data": 2}, {"model": 4})
    assert mesh.axis_names == ("data", "model")
    assert dict(mesh.shape) == {"data": 2, "model": 4}
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    # every ICI (model) row is one contiguous device block
    for row in ids:
        assert list(row) == list(range(row[0], row[0] + 4)), ids

    with pytest.raises(ValueError, match="exactly one"):
        build_hybrid_mesh({"data": 2}, {"data": 4})
    with pytest.raises(ValueError, match="devices"):
        build_hybrid_mesh({"data": 3}, {"model": 4})


def test_hybrid_mesh_trains_dp_over_tp(jax):
    """A DP(x2 slices) x TP(x4) step runs end to end on the hybrid mesh:
    the same Trainer, with TP rules constraining the state layout."""
    import numpy as np
    import optax

    from tensorflowonspark_tpu import training
    from tensorflowonspark_tpu.parallel import build_hybrid_mesh

    mesh = build_hybrid_mesh({"data": 2}, {"model": 4})

    import flax.linen as nn

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(8)(x)

    trainer = training.Trainer(TinyMLP(), optax.sgd(0.1), mesh,
                               constrain_state=False, donate_state=False)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 12).astype(np.float32)
    y = (np.arange(16) % 8).astype(np.int64)
    batch = jax.device_put({"x": x, "y": y}, trainer.batch_sharding)
    state = trainer.init(jax.random.PRNGKey(0), x)
    state, metrics = trainer.step(state, batch)
    loss0 = float(metrics["loss"])
    for _ in range(5):
        state, metrics = trainer.step(state, batch)
    assert float(metrics["loss"]) < loss0


def test_zigzag_roundtrip_and_ring_parity(jax):
    """to_zigzag/from_zigzag invert; causal ring+flash over the zigzag
    layout matches the oracle exactly (after undoing the permutation)."""
    import numpy as np

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        from_zigzag, reference_attention, ring_flash_attention, to_zigzag)

    mesh = build_mesh({"seq": 8})
    B, S, N, D = 1, 8 * 16, 2, 8
    rng = np.random.RandomState(7)
    q = rng.randn(B, S, N, D).astype(np.float32)

    zz = to_zigzag(q, 8)
    np.testing.assert_array_equal(np.asarray(from_zigzag(zz, 8)), q)

    import jax as _jax

    out_zz = _jax.jit(lambda x: ring_flash_attention(
        x, x, x, mesh, causal=True, block_q=8, block_k=8,
        interpret=True, layout="zigzag"))(to_zigzag(q, 8))
    got = np.asarray(from_zigzag(out_zz, 8))
    want = np.asarray(reference_attention(q, q, q, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_zigzag_grads_match_reference(jax):
    """Differentiability through the zigzag schedule: d(loss)/d(q,k,v)
    equals the oracle's gradients (permutation undone)."""
    import numpy as np

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        from_zigzag, reference_attention, ring_flash_attention, to_zigzag)

    mesh = build_mesh({"seq": 4}, devices=jax.devices()[:4])
    B, S, N, D = 1, 4 * 16, 2, 8
    rng = np.random.RandomState(8)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)
    w = rng.randn(B, S, N, D).astype(np.float32)  # fixed cotangent-ish

    def loss_zz(q_, k_, v_):
        out = ring_flash_attention(
            to_zigzag(q_, 4), to_zigzag(k_, 4), to_zigzag(v_, 4), mesh,
            causal=True, block_q=8, block_k=8, interpret=True,
            layout="zigzag")
        return (from_zigzag(out, 4) * w).sum()

    def loss_ref(q_, k_, v_):
        return (reference_attention(q_, k_, v_, causal=True) * w).sum()

    g_zz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_zz, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_zigzag_rejects_bad_configs(jax):
    import numpy as np
    import pytest as _pytest

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.ring_attention import (
        ring_flash_attention, to_zigzag)

    mesh = build_mesh({"seq": 8})
    q = np.zeros((1, 8 * 16, 2, 8), np.float32)
    with _pytest.raises(ValueError, match="causal"):
        ring_flash_attention(q, q, q, mesh, causal=False, layout="zigzag")
    with _pytest.raises(ValueError, match="layout"):
        ring_flash_attention(q, q, q, mesh, causal=True, layout="spiral")
    with _pytest.raises(ValueError, match="divisible"):
        to_zigzag(np.zeros((1, 24, 2, 8), np.float32), 8)


def test_longcontext_zigzag_matches_contiguous(jax):
    """The long-context LM trains identically (same loss trajectory, up
    to float reassociation) in zigzag and contiguous layouts — the
    permutation must be semantics-free end to end."""
    from examples.longcontext import long_dist

    kwargs = dict(seq_len=8 * 32, batch=1, vocab=16, hidden=32, heads=2,
                  layers=1, period=11, steps=6, block=16, interpret=True,
                  log_every=0)
    f_c, l_c = long_dist.train(layout="contiguous", **kwargs)
    f_z, l_z = long_dist.train(layout="zigzag", **kwargs)
    assert abs(f_c - f_z) < 1e-3, (f_c, f_z)
    assert abs(l_c - l_z) < 5e-2 * max(abs(l_c), 1e-3), (l_c, l_z)
    assert l_z < f_z  # and it actually learns in the zigzag layout


def test_tree_shardings_indivisible_dim_replicates():
    """A rule dim that doesn't divide its mesh axis degrades to a
    replicated dim instead of a device_put error — BERT's [2-head]
    biases at tp=4 (found by scripts/tp_scaling_model.py)."""
    import jax
    import numpy as np

    from tensorflowonspark_tpu.models import bert
    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.sharding import (
        BERT_TP_RULES, tree_shardings)

    mesh = build_mesh({"data": 2, "model": 4})
    cfg = bert.bert_tiny()  # 2 heads: head-sharded dims can't split by 4
    model = bert.BertForQuestionAnswering(cfg)
    x = np.zeros((4, 16), np.int32)
    params = model.init(jax.random.PRNGKey(0), x,
                        np.ones((4, 16), bool), deterministic=True)["params"]
    shardings = tree_shardings(params, mesh, BERT_TP_RULES)
    placed = jax.device_put(params, shardings)  # must not raise

    def spec_of(pattern):
        import re
        for path, leaf in jax.tree_util.tree_flatten_with_path(placed)[0]:
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            if re.search(pattern, name):
                return tuple(leaf.sharding.spec)
        raise AssertionError(pattern + " not found")

    # the 2-head bias dim CANNOT split by 4: must have degraded to
    # replicated, while the 64-wide ffn kernel keeps its model axis —
    # an implementation that replicates everything must fail here
    assert spec_of(r"attention/query/bias")[:1] == (None,)
    assert "model" in spec_of(r"ffn_in/kernel")


# -- elastic resize: respec_for_width + mesh-construction errors -----------

def test_respec_for_width_shrinks_and_grows_data_axis():
    from tensorflowonspark_tpu.parallel.mesh import respec_for_width

    # shrink and grow: only the data axis moves, order preserved
    assert respec_for_width({"data": 2, "model": 4}, 4) == \
        {"data": 1, "model": 4}
    assert respec_for_width({"data": 1, "model": 4}, 8) == \
        {"data": 2, "model": 4}
    assert list(respec_for_width({"model": 2, "data": 4}, 16)) == \
        ["model", "data"]
    assert respec_for_width({"model": 2, "data": 4}, 16)["data"] == 8
    # pure-DP default, and a missing data axis is inserted outermost
    assert respec_for_width(None, 3) == {"data": 3}
    assert list(respec_for_width({"model": 2}, 8)) == ["data", "model"]
    assert respec_for_width({"model": 2}, 8) == {"data": 4, "model": 2}
    # a -1 DATA axis is fine (it is being replaced anyway)
    assert respec_for_width({"data": -1, "model": 2}, 6) == \
        {"data": 3, "model": 2}


def test_respec_for_width_loud_errors_name_the_axes():
    import pytest as _pytest

    from tensorflowonspark_tpu.parallel.mesh import respec_for_width

    # fixed axes that cannot factor: error names them and the floor
    with _pytest.raises(ValueError, match=r"model.*4"):
        respec_for_width({"data": 2, "model": 4}, 6)
    with _pytest.raises(ValueError, match="multiples of 4"):
        respec_for_width({"data": 2, "model": 4}, 2)
    # a -1 NON-data axis cannot be respec'd
    with _pytest.raises(ValueError, match="model"):
        respec_for_width({"data": 2, "model": -1}, 8)
    with _pytest.raises(ValueError):
        respec_for_width({"data": 2}, 0)


def test_build_mesh_error_split_names_failing_axis(jax):
    """The two -1 inference failures are distinct errors naming the
    axis (satellite: the old message conflated 'another axis is 0'
    with 'device count does not divide')."""
    import pytest as _pytest

    from tensorflowonspark_tpu.parallel import build_mesh

    with _pytest.raises(ValueError, match=r"infer axis 'data'.*size 0"):
        build_mesh({"data": -1, "model": 0})
    with _pytest.raises(ValueError,
                        match=r"infer axis 'data'.*do not divide"):
        build_mesh({"data": -1, "model": 3})  # 8 % 3 != 0
    # the known==0 case names the ZERO axis, not the inferred one
    with _pytest.raises(ValueError, match=r"\['model'\]"):
        build_mesh({"data": -1, "model": 0})


# -- build_hybrid_mesh table tests (satellite: no direct coverage) ---------

def test_build_hybrid_mesh_rejects_axis_overlap(jax):
    import pytest as _pytest

    from tensorflowonspark_tpu.parallel.mesh import build_hybrid_mesh

    with _pytest.raises(ValueError, match="exactly one"):
        build_hybrid_mesh({"data": 2}, {"data": 4})


def test_build_hybrid_mesh_infers_minus_one(jax):
    from tensorflowonspark_tpu.parallel.mesh import build_hybrid_mesh

    import pytest as _pytest

    # -1 on the dcn side and on the ici side, inferred from 8 devices
    mesh = build_hybrid_mesh({"data": -1}, {"model": 4})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
        {"data": 2, "model": 4}
    mesh = build_hybrid_mesh({"data": 2}, {"model": -1})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
        {"data": 2, "model": 4}
    # at most one -1 ACROSS both dicts
    with _pytest.raises(ValueError, match="at most one"):
        build_hybrid_mesh({"data": -1}, {"model": -1})
    # non-factoring inference names the axis
    with _pytest.raises(ValueError, match=r"hybrid axis 'data'"):
        build_hybrid_mesh({"data": -1}, {"model": 3})
    with _pytest.raises(ValueError, match=r"\['model'\]"):
        build_hybrid_mesh({"data": -1}, {"model": 0})


def test_build_hybrid_mesh_single_slice_fallback_ordering(jax):
    """CPU/single-slice fallback: slice-major contiguous blocks — DCN
    axes outermost over jax.devices()' process-major order, ICI axes
    contiguous within a block."""
    import numpy as np

    from tensorflowonspark_tpu.parallel.mesh import build_hybrid_mesh

    devices = jax.devices()
    mesh = build_hybrid_mesh({"data": 2}, {"model": 4})
    assert mesh.axis_names == ("data", "model")
    grid = mesh.devices
    assert grid.shape == (2, 4)
    # row i holds devices[i*4:(i+1)*4] in order: an ici axis never
    # crosses a block boundary
    for i in range(2):
        for j in range(4):
            assert grid[i, j] is devices[i * 4 + j]
    # flattening recovers the original global device order
    assert list(grid.flatten()) == list(devices)
