"""Chaos pass on the feed plane (VERDICT r4 task 7; deflaked + folded
into the chaos harness in PR 3).

SIGKILL is the one exit that runs no handlers: no atexit, no except, no
queue puts. These tests kill real processes at the worst moments —
trainer mid-shm-write (feeder blocked inside the ring), trainer
mid-queue-join, the whole feeder/executor process mid-feed — and assert
the three survival properties the reference's feed plane lacked
(SURVEY.md §5 failure detection): no wedged feeder, a driver-side error
that names the death, and no leaked /dev/shm segments afterwards.

The kill choreography lives in chaos.py, not here: trainer-side kills
are armed injection points (``TFOS_CHAOS`` rides executor_env into the
forked trainer) fired at instrumented framework sites, and the
out-of-process executor kill uses ``chaos.kill_when`` — every wait is
event/deadline polling (``chaos.poll_until``), never a fixed sleep. The
two load-sensitive variants VERDICT r5 flagged were flaky precisely
because each test re-derived this logic with its own sleeps.

Run via ``make chaos`` (serial, per-test wall-clock caps); the ``chaos``
marker keeps the suite out of the tier-1 ``not slow`` gate.
"""

import glob
import os
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, cluster, shm
from tensorflowonspark_tpu.engine import Context
from tensorflowonspark_tpu.engine.context import TaskError

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.slow,
    pytest.mark.skipif(not shm.available(),
                       reason="native shm ring unavailable"),
]

RING_CAPACITY = 64 * 1024 * 1024  # the MIN_USEFUL_CAPACITY floor


def _rings():
    return glob.glob("/dev/shm/tfos-*")


def _sc(tmp_path, transport, n=1, chaos_spec=None):
    env = {"TFOS_FEED_TRANSPORT": transport,
           "TFOS_SHM_CAPACITY": str(RING_CAPACITY)}
    if chaos_spec:
        # the executor exports it; fork/spawn hands it to the trainer,
        # whose instrumented sites (datafeed.next_batch) fire the kill
        env[chaos.ENV_VAR] = chaos_spec
    return Context(num_executors=n, work_root=str(tmp_path / "engine"),
                   executor_env=env)


def test_trainer_sigkill_mid_shm_write(tmp_path):
    """Feeder blocked INSIDE ring.write when the trainer dies: the bounded
    write's state check must abort the feed (no wedge), shutdown must
    surface the kill, and the ring must not leak.

    Kill site: ``kill_trainer_at_batch=1`` — SIGKILL inside the first
    ``next_batch`` return, while the feeder still has ~96MB to push
    through a 64MB ring."""
    def read_batches(args, ctx):
        feed = ctx.get_data_feed(train_mode=True)
        while not feed.should_stop():
            feed.next_batch(8)  # chaos fires inside the first call

    sc = _sc(tmp_path, "shm", chaos_spec="kill_trainer_at_batch=1")
    try:
        tfc = cluster.run(sc, read_batches, {}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        # > capacity + one in-flight chunk, so the feeder is guaranteed
        # to be blocked in a ring write when the trainer is gone:
        # 1536 x 64KB float32 rows = 96MB vs a 64MB ring
        rows = [np.zeros(16384, np.float32) for _ in range(1536)]
        t0 = time.monotonic()
        # train-path contract: the feeder ABORTS its blocked write when
        # the watchdog flips state (no wedge, no 60s timeout burn) and
        # returns — the real error surfaces at shutdown() below
        tfc.train(sc.parallelize(rows, 2), feed_timeout=60)
        assert time.monotonic() - t0 < 45, "feeder wedged past its bounds"
        with pytest.raises(RuntimeError, match=r"-9|killed"):
            tfc.shutdown(grace_secs=1)
    finally:
        sc.stop()
    assert not _rings(), _rings()


def test_trainer_sigkill_mid_queue_join(tmp_path):
    """Feeder parked in the queue join when the trainer dies: the chunked
    join's state check must return (the reference's bare queue.join()
    hangs here forever), and shutdown must name the exit code.

    Kill site: ``kill_trainer_when_queued=1`` — fires on the first
    batch served while the trainer holds the partition's UNCONSUMED
    EndPartition marker (it rides the feeder's tail-coalesced final
    put), which proves the feeder finished writing and is parked in
    its join on the owed task_done — an event, not a timing guess.
    This is the deflaked form of the VERDICT-r5 flake: the old
    trainer-side qsize poll raced the feeder under load."""
    def read_batches(args, ctx):
        feed = ctx.get_data_feed(train_mode=True)
        while not feed.should_stop():
            feed.next_batch(8)  # chaos polls + fires inside the first call

    sc = _sc(tmp_path, "queue", chaos_spec="kill_trainer_when_queued=1")
    try:
        tfc = cluster.run(sc, read_batches, {}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        # small feed: fully written long before the trainer dies, so the
        # feeder is inside _join_feed when the kill lands
        t0 = time.monotonic()
        tfc.train(sc.parallelize(list(range(200)), 2), feed_timeout=60)
        # generous bound (feed_timeout + load margin): the assertion is
        # "returned at all, via the state check" — not a latency SLO a
        # loaded CI box can miss
        assert time.monotonic() - t0 < 120, "join wedged past its bounds"
        with pytest.raises(RuntimeError, match=r"-9|killed"):
            tfc.shutdown(grace_secs=1)
    finally:
        sc.stop()
    assert not _rings(), _rings()


def test_feeder_executor_sigkill_leaves_no_ring(tmp_path):
    """SIGKILL the whole executor (feeder + broker + ring owner) mid-feed:
    the driver must surface the death, the orphaned trainer must abort on
    its own (dead broker), and engine stop must sweep the leaked ring.

    Kill site: ``chaos.kill_when`` from the test process — the trainer
    cannot shoot its own executor (the injection points are in-process),
    so the harness's out-of-process assassin owns this choreography:
    trigger = the trainer's pid file landing (its first consumed batch
    proved the feed is flowing), settle = a floor for the feeder to be
    mid-write again, and a missed trigger means no kill at all — the
    positive assertion below then fails loudly, not flakily."""
    def record_pid_and_crawl(args, ctx):
        # after the first real batch proves the feed is flowing, publish
        # our pid (the assassin's trigger), then consume slowly so the
        # feeder stays mid-write when the executor is shot
        feed = ctx.get_data_feed(train_mode=True)
        feed.next_batch(1)
        with open(args["pid_file"], "w") as f:
            f.write(str(os.getpid()))
        while not feed.should_stop():
            feed.next_batch(1)
            time.sleep(0.05)

    pid_file = str(tmp_path / "trainer.pid")
    sc = _sc(tmp_path, "shm")
    try:
        tfc = cluster.run(sc, record_pid_and_crawl,
                          {"pid_file": pid_file}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        assert _rings(), "ring should exist while the cluster is live"
        # small enough that the orphan can drain the ring's leftovers
        # (at its crawl pace) and reach the dead-broker abort within the
        # deadline; the blocked-mid-write abort is test 1's job
        rows = [np.zeros(16384, np.float32) for _ in range(256)]
        executor_pid = sc._procs[0].pid
        killer = chaos.kill_when(
            lambda: executor_pid,
            trigger=lambda: os.path.exists(pid_file),
            settle=0.5, deadline=60)
        with pytest.raises(TaskError, match="died|connection lost"):
            tfc.train(sc.parallelize(rows, 2), feed_timeout=60)
        killer.join(timeout=60)
        assert not killer.is_alive(), "assassin thread wedged"
        # the kill skipped every cleanup: the segment is leaked right now
        assert _rings(), "expected the SIGKILLed executor's ring to linger"

        # the orphaned trainer must notice its broker is gone and exit.
        # Deadline sized for a loaded 1-core box: the orphan first crawls
        # the ring's leftovers at its deliberate 0.05s/record pace (up to
        # ~13s unloaded), then needs a 5s read timeout + the dead-broker
        # RPC to error out — 120s is a no-hang bound, not a latency SLO.
        trainer_pid = int(open(pid_file).read())

        def _trainer_gone():
            try:
                os.kill(trainer_pid, 0)
                return False
            except ProcessLookupError:
                return True

        assert chaos.poll_until(_trainer_gone, timeout=120, interval=0.5), \
            "orphaned trainer still alive after 120s"
    finally:
        sc.stop()
    # stop() swept the dead executor's ring (pid-liveness check)
    assert not _rings(), _rings()
