"""Chaos pass on the feed plane (VERDICT r4 task 7).

SIGKILL is the one exit that runs no handlers: no atexit, no except, no
queue puts. These tests kill real processes at the worst moments —
trainer mid-shm-write (feeder blocked inside the ring), trainer
mid-queue-join, the whole feeder/executor process mid-feed — and assert
the three survival properties the reference's feed plane lacked
(SURVEY.md §5 failure detection): no wedged feeder, a driver-side error
that names the death, and no leaked /dev/shm segments afterwards.
"""

import glob
import os
import signal
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster, shm
from tensorflowonspark_tpu.engine import Context
from tensorflowonspark_tpu.engine.context import TaskError

pytestmark = pytest.mark.skipif(
    not shm.available(), reason="native shm ring unavailable")

RING_CAPACITY = 64 * 1024 * 1024  # the MIN_USEFUL_CAPACITY floor


def _rings():
    return glob.glob("/dev/shm/tfos-*")


def _sc(tmp_path, transport, n=1):
    return Context(
        num_executors=n, work_root=str(tmp_path / "engine"),
        executor_env={"TFOS_FEED_TRANSPORT": transport,
                      "TFOS_SHM_CAPACITY": str(RING_CAPACITY)})


def test_trainer_sigkill_mid_shm_write(tmp_path):
    """Feeder blocked INSIDE ring.write when the trainer dies: the bounded
    write's state check must abort the feed (no wedge), shutdown must
    surface the kill, and the ring must not leak."""
    def read_one_then_sigkill(args, ctx):
        # trainer: prove the feed is live, then die the ugly way
        feed = ctx.get_data_feed(train_mode=True)
        feed.next_batch(8)
        os.kill(os.getpid(), signal.SIGKILL)

    sc = _sc(tmp_path, "shm")
    try:
        tfc = cluster.run(sc, read_one_then_sigkill, {}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        # > capacity + one in-flight chunk, so the feeder is guaranteed
        # to be blocked in a ring write when the trainer is gone:
        # 1536 x 64KB float32 rows = 96MB vs a 64MB ring
        rows = [np.zeros(16384, np.float32) for _ in range(1536)]
        t0 = time.monotonic()
        # train-path contract: the feeder ABORTS its blocked write when
        # the watchdog flips state (no wedge, no 60s timeout burn) and
        # returns — the real error surfaces at shutdown() below
        tfc.train(sc.parallelize(rows, 2), feed_timeout=60)
        assert time.monotonic() - t0 < 45, "feeder wedged past its bounds"
        with pytest.raises(RuntimeError, match=r"-9|killed"):
            tfc.shutdown(grace_secs=1)
    finally:
        sc.stop()
    assert not _rings(), _rings()


def test_trainer_sigkill_mid_queue_join(tmp_path):
    """Feeder parked in the queue join when the trainer dies: the chunked
    join's state check must return (the reference's bare queue.join()
    hangs here forever), and shutdown must name the exit code."""
    def read_one_then_sigkill_after(args, ctx):
        # consume one batch, then die — but only once the feeder has
        # finished writing the partition and is (about to be) parked in
        # its join. Poll-with-deadline, not a fixed linger: on a loaded
        # 1-core box a fixed sleep races the feeder both ways. The
        # EndPartition marker landing in the input queue (qsize >= 1
        # after this trainer consumed the partition's one chunk) IS the
        # "feeder finished writing" event.
        feed = ctx.get_data_feed(train_mode=True)
        feed.next_batch(8)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and feed._queue_in.qsize() < 1:
            time.sleep(0.1)
        os.kill(os.getpid(), signal.SIGKILL)

    sc = _sc(tmp_path, "queue")
    try:
        tfc = cluster.run(sc, read_one_then_sigkill_after, {},
                          num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        # small feed: fully written long before the trainer dies, so the
        # feeder is inside _join_feed when the kill lands
        t0 = time.monotonic()
        tfc.train(sc.parallelize(list(range(200)), 2), feed_timeout=60)
        # generous bound (feed_timeout + load margin): the assertion is
        # "returned at all, via the state check" — not a latency SLO a
        # loaded CI box can miss
        assert time.monotonic() - t0 < 120, "join wedged past its bounds"
        with pytest.raises(RuntimeError, match=r"-9|killed"):
            tfc.shutdown(grace_secs=1)
    finally:
        sc.stop()
    assert not _rings(), _rings()


def test_feeder_executor_sigkill_leaves_no_ring(tmp_path):
    """SIGKILL the whole executor (feeder + broker + ring owner) mid-feed:
    the driver must surface the death, the orphaned trainer must abort on
    its own (dead broker), and engine stop must sweep the leaked ring."""
    def record_pid_and_crawl(args, ctx):
        # after the first real batch proves the feed is flowing, publish
        # our pid (the test's kill signal), then consume slowly so the
        # feeder stays mid-write when the executor is shot
        feed = ctx.get_data_feed(train_mode=True)
        feed.next_batch(1)
        with open(args["pid_file"], "w") as f:
            f.write(str(os.getpid()))
        while not feed.should_stop():
            feed.next_batch(1)
            time.sleep(0.05)

    pid_file = str(tmp_path / "trainer.pid")
    sc = _sc(tmp_path, "shm")
    try:
        tfc = cluster.run(sc, record_pid_and_crawl,
                          {"pid_file": pid_file}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        assert _rings(), "ring should exist while the cluster is live"
        # small enough that the orphan can drain the ring's leftovers
        # (at its crawl pace) and reach the dead-broker abort within the
        # deadline; the blocked-mid-write abort is test 1's job
        rows = [np.zeros(16384, np.float32) for _ in range(256)]
        executor_pid = sc._procs[0].pid

        import threading

        def assassin():
            # wait for the trainer to prove the feed is flowing (the pid
            # file lands after its first consumed batch), then shoot the
            # executor while its feed task is mid-feed. Poll-with-
            # deadline; the deadline is generous because missing it just
            # means the kill never fires and train() below succeeds —
            # which fails the pytest.raises loudly, not flakily.
            deadline = time.monotonic() + 60
            while not os.path.exists(pid_file):
                if time.monotonic() > deadline:
                    return
                time.sleep(0.1)
            time.sleep(0.5)  # minimum settle, not a deadline: the feeder
            # is still streaming 256 slow-consumed rows at this point
            os.kill(executor_pid, signal.SIGKILL)

        killer = threading.Thread(target=assassin, daemon=True)
        killer.start()
        with pytest.raises(TaskError, match="died|connection lost"):
            tfc.train(sc.parallelize(rows, 2), feed_timeout=60)
        killer.join(timeout=60)
        assert not killer.is_alive(), "assassin thread wedged"
        # the kill skipped every cleanup: the segment is leaked right now
        assert _rings(), "expected the SIGKILLed executor's ring to linger"

        # the orphaned trainer must notice its broker is gone and exit.
        # Deadline sized for a loaded 1-core box: the orphan first crawls
        # the ring's leftovers at its deliberate 0.05s/record pace (up to
        # ~13s unloaded), then needs a 5s read timeout + the dead-broker
        # RPC to error out — 120s is a no-hang bound, not a latency SLO.
        trainer_pid = int(open(pid_file).read())
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                os.kill(trainer_pid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.5)
        else:
            pytest.fail("orphaned trainer still alive after 120s")
    finally:
        sc.stop()
    # stop() swept the dead executor's ring (pid-liveness check)
    assert not _rings(), _rings()
