"""Control-plane survivability (PR 19): router warm-standby takeover,
headless-fleet recovery, and the control-epoch admin fence.

Three layers, matching the PR's design:

- UNIT — the ModelServer control-epoch gate (adopt-at-or-above,
  409-below, malformed-header 400, unstamped back-compat), the
  supervisor's and autoscaler's recovery-grace gating (a restarted
  journal-seeded reservation server's empty lease table is a recovery
  artifact, not fleet death), and the new chaos points' fire/latch
  semantics.
- E2E chaos (slow + chaos markers, collected by ``make chaos``) —
  the reservation server SIGKILLed mid-traffic (in-process
  ``Server.crash()``: listener dead, lease table dropped) and
  restarted from its journal: ZERO client-visible failures, replicas
  re-register with the SAME epoch, post-restart mints are strictly
  greater, teardown after a control-plane death stays bounded.
- E2E takeover — a warm RouterStandby promotes itself after leader
  death at a deterministic dispatch (``kill_router_at_request``),
  mints a higher control epoch, and the deposed leader's stamped
  admin writes are refused 409 ControlFenced; the replica-side dedup
  window (keyed X-TFOS-Request-Id) survives the router swap, so a
  retried request is REPLAYED, never re-executed.

The journal itself (floors, torn tails, SIGKILL property tests) is
tests/test_controlstate.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tensorflowonspark_tpu import (autoscale, chaos, fleet, reservation,
                                   serving, supervisor, tracing)
from tensorflowonspark_tpu.models.decoder import DecoderLM

V, H, NH, L, MAXLEN = 17, 32, 4, 2, 48


@pytest.fixture(scope="module")
def lm():
    train = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                      max_len=MAXLEN, decode=False)
    dec = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                    max_len=MAXLEN, decode=True)
    params = train.init(jax.random.PRNGKey(7),
                        jnp.zeros((2, MAXLEN), jnp.int32))["params"]
    return dec, params


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.disarm()


def _post(url, payload, timeout=120, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- UNIT: the ModelServer control-epoch fence -----------------------------

class _StubEngine(object):
    """Just enough engine surface for an admin-plane-only ModelServer."""

    name = "m"

    def __init__(self, replica_id="r0"):
        self.replica_id = replica_id
        self.metrics = tracing.MetricsRegistry()
        self.counters = tracing.Counters()

    def stop(self):
        pass


def test_control_fence_adopts_at_or_above_refuses_below():
    s = serving.ModelServer(None, engine=_StubEngine(), name="m", port=0)
    assert s.control_epoch_floor() == 0
    assert s.admit_control_epoch(5) == (True, 5)   # adopt
    assert s.admit_control_epoch(5) == (True, 5)   # at-floor: admitted
    assert s.admit_control_epoch(4) == (False, 5)  # below: refused
    assert s.admit_control_epoch(9) == (True, 9)   # newer leader


def test_control_fence_http_409_400_and_unstamped_passthrough():
    eng = _StubEngine()
    s = serving.ModelServer(None, engine=eng, name="m", port=0)
    host, port = s.start()
    base = "http://%s:%d" % (host, port)
    try:
        # a takeover broadcast raises the floor
        st, body = _post(base + "/admin/control_fence",
                         {"control_epoch": 7},
                         headers={"X-TFOS-Control-Epoch": "7"})
        assert (st, body) == (200, {"control_epoch": 7})
        # the deposed leader's stamped write: 409, typed kind, floor
        st, body = _post(base + "/admin/ship_fence",
                         {"replica_id": "x", "min_epoch": 1},
                         headers={"X-TFOS-Control-Epoch": "3"})
        assert st == 409
        assert body["kind"] == "ControlFenced"
        assert body["control_epoch"] == 7
        # refusals are counted (tfos_control_admin_rejections_total)
        counts = eng.metrics.snapshot()["counters"]["tfos_control"]
        assert counts["counts"]["admin_rejections"] == 1
        # malformed stamp: a 400, never a silent pass
        st, body = _post(base + "/admin/ship_fence",
                         {"replica_id": "x", "min_epoch": 1},
                         headers={"X-TFOS-Control-Epoch": "bogus"})
        assert st == 400
        # UNSTAMPED writes pass (pre-PR-19 drivers keep working)
        st, _ = _post(base + "/admin/ship_fence",
                      {"replica_id": "x", "min_epoch": 1})
        assert st == 200
    finally:
        s.stop()


# -- UNIT: recovery-grace gating (supervisor + autoscaler) -----------------

class _RecoveringReservation(object):
    def __init__(self, recovering=True):
        self._recovering = recovering
        self.snapshot = {}

    def recovering(self):
        return self._recovering

    def serving_snapshot(self):
        return dict(self.snapshot)

    def lease_epoch(self, rid):
        return (self.snapshot.get(rid) or {}).get("epoch")


class _HoldStubRouter(object):
    def __init__(self):
        self.holds = []

    def quiesce(self, rid, reason="", owner="operator"):
        self.holds.append(("quiesce", rid, owner))

    def readmit(self, rid, owner="operator"):
        self.holds.append(("readmit", rid, owner))


def test_supervisor_lease_watch_holds_fire_during_recovery():
    """Right after a journal-seeded reservation restart the lease
    table is EMPTY by construction (replicas repopulate it with their
    next beats). The supervisor's serving-lease watch must read that
    as a recovery artifact — no quiesce, no loss events — until the
    grace clears; then classification resumes as usual."""

    class _Remote(object):
        remote = True
        replica_id = "replica-0"
        executor_id = "e0"

    class _Fleet(object):
        def __init__(self):
            self.replicas = [_Remote()]
            self.reservation = _RecoveringReservation(recovering=True)
            self.router = _HoldStubRouter()

    fleet_stub = _Fleet()
    sup = supervisor.Supervisor()
    sup._serving_watch = {"fleet": fleet_stub, "stale_after": 1.0,
                          "reported": set()}
    sup._check_serving_leases()  # empty snapshot + recovering
    assert fleet_stub.router.holds == [], \
        "recovery-window emptiness classified as replica death"
    assert not sup.events.events("serving_replica_lost")
    # grace cleared, lease still missing: NOW it is a real death
    fleet_stub.reservation._recovering = False
    sup._check_serving_leases()
    assert ("quiesce", "replica-0", "supervisor") \
        in fleet_stub.router.holds


def test_autoscaler_holds_during_recovery():
    """The post-restart empty snapshot reads as age-None views — the
    REPLACE signature. Scaling on it would spawn replacements (fresh
    epochs!) for replicas that are alive and about to re-announce."""

    class _R(object):
        def __init__(self, rid):
            self.replica_id = rid

    class _Fleet(object):
        placement = "driver"
        router = None

        def __init__(self):
            self.replicas = [_R("replica-0")]
            self.reservation = _RecoveringReservation(recovering=True)

    stub = _Fleet()
    ctl = autoscale.AutoscaleController(stub)
    d = ctl.poll_once()
    assert d.action == autoscale.ScaleDecision.HOLD
    assert "recovering" in d.reason
    # the hold is a decision, not a skipped poll: counted + recorded
    assert ctl.counters.snapshot()["counts"]["decisions"] == 1


# -- UNIT: the new chaos points --------------------------------------------

def test_chaos_kill_reservation_server_point_fires_once():
    chaos.arm("kill_reservation_server=3")
    assert not chaos.on_reservation_beat(2)
    assert chaos.on_reservation_beat(3)
    # single-shot: the fired latch survives an in-process restart, so
    # the restarted server is never re-killed at the same beat count
    assert not chaos.on_reservation_beat(99)


def test_chaos_kill_router_at_request_scopes_by_name():
    chaos.arm("kill_router_at_request=2,only=lm")
    assert not chaos.on_router_request(5, ident="other")
    assert not chaos.on_router_request(1, ident="lm")
    assert chaos.on_router_request(2, ident="lm")
    assert not chaos.on_router_request(3, ident="lm")


# -- E2E: reservation-server death + journal-seeded restart ----------------

@pytest.mark.slow
@pytest.mark.chaos
def test_reservation_bounce_zero_failures_same_epochs(lm, tmp_path):
    """The headless-fleet acceptance e2e: chaos SIGKILLs the
    reservation server at the N-th BEAT (in-process ``crash()`` —
    lease state gone, reply never sent), the fleet keeps serving
    HEADLESS (beat loops back off with jitter, replicas never stop
    answering), and ``schedule_reservation_restart`` brings the
    driver back from the journal. Pins: zero client-visible failures,
    every replica re-registers with the SAME epoch it already held,
    reconnects are counted, ``recovering()`` clears on re-announce,
    and a post-restart mint is strictly above every pre-crash epoch."""
    dec, params = lm
    journal = str(tmp_path / "control.journal")
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2}, beat_interval=0.1,
                            journal=journal) as f:
        url = f.url("/v1/models/lm:generate")
        _post(url, {"prompt": [1, 2, 3], "max_new_tokens": 2})  # warm
        pre_epochs = {r.replica_id: r.epoch for r in f.replicas}
        assert all(e is not None for e in pre_epochs.values())

        chaos.arm("kill_reservation_server=8;"
                  "restart_reservation_after=0.4")
        restarter = chaos.schedule_reservation_restart(f)
        dead_server = f.reservation

        failures, ok = [], []
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    st, body = _post(url, {"prompt": [1, 2, 3],
                                           "max_new_tokens": 4})
                    (ok if st == 200 else failures).append((st, body))
                except Exception as e:  # noqa: BLE001 - the assertion
                    failures.append(("exc", repr(e)))
                time.sleep(0.05)

        t = threading.Thread(target=client, daemon=True,
                             name="tfos-test-bounce-client")
        t.start()
        try:
            assert chaos.poll_until(dead_server.done.is_set,
                                    timeout=30), "chaos kill never fired"
            restarter.join(timeout=30)
            assert f.reservation is not dead_server, "never restarted"
            # replicas re-announce with the SAME epoch (no re-mint:
            # the incumbents were never superseded)
            assert chaos.poll_until(
                lambda: {k: v.get("epoch") for k, v in
                         f.reservation.serving_snapshot().items()
                         } == pre_epochs,
                timeout=30), "replicas never re-registered"
            assert chaos.poll_until(
                lambda: not f.reservation.recovering(), timeout=30)
            # a few more requests through the healed plane
            time.sleep(0.5)
        finally:
            stop.set()
            t.join(timeout=30)
        assert ok, "no traffic made it through at all"
        assert not failures, \
            "client-visible failures across the bounce: %s" % failures[:3]
        # every reconnect was survived, counted, and exported
        for r in f.replicas:
            assert r.beat_reconnects >= 1, r.replica_id
            assert r.engine.counters.snapshot()["counts"].get(
                "beat_reconnects", 0) >= 1, r.replica_id
        # durable floors: a fresh mint lands strictly above the
        # pre-crash epoch even though the server restarted
        assert f.reservation.mint_epoch("some-new-identity") == 1
        fenced_rid = f.replicas[0].replica_id
        assert f.reservation.mint_epoch(fenced_rid) \
            > pre_epochs[fenced_rid]
        f.replicas[0].re_register()  # undo the probe mint's fence


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_stop_bounded_after_reservation_crash(lm, tmp_path):
    """Teardown wall-time pin: ``ServingFleet.stop()`` after the
    reservation server died must complete in bounded time — the beat
    loops' in-flight reconnect attempts are aborted out-of-band
    (Client.abort), not waited out."""
    dec, params = lm
    journal = str(tmp_path / "control.journal")
    f = fleet.ServingFleet(dec, params, replicas=2, name="lm",
                           engine_kw={"slots": 2}, beat_interval=0.1,
                           journal=journal)
    f.start()
    _post(f.url("/v1/models/lm:generate"),
          {"prompt": [1, 2, 3], "max_new_tokens": 2})
    f.reservation.crash()
    t0 = time.monotonic()
    f.stop()
    took = time.monotonic() - t0
    assert took < 10.0, \
        "teardown hung %.1fs waiting on a dead reservation server" % took


# -- E2E: router warm-standby takeover -------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_router_standby_takeover_fences_old_leader(lm, tmp_path):
    """Leader death at a deterministic dispatch: chaos crashes the
    router on its K-th request (listener closed mid-traffic, no
    drain); the warm standby confirms death over consecutive probes,
    mints a HIGHER control epoch, starts a fresh router on the same
    replica set, and broadcasts the new floor. Pins: the standby
    serves within a bounded window; the deposed leader's stamped
    admin write is refused 409 ControlFenced (split-brain cannot
    write); the old listener is actually dead (no request can be
    served by both); the replica-side dedup window survives the swap
    (a retried X-TFOS-Request-Id is REPLAYED, not re-executed)."""
    dec, params = lm
    journal = str(tmp_path / "control.journal")
    with fleet.ServingFleet(dec, params, replicas=2, name="lm",
                            engine_kw={"slots": 2}, beat_interval=0.1,
                            journal=journal) as f:
        url = f.url("/v1/models/lm:generate")
        _post(url, {"prompt": [1, 2, 3], "max_new_tokens": 2})  # warm
        old_router = f.router
        old_addr = old_router.addr
        old_epoch = f.control_epoch
        assert old_epoch is not None and old_epoch >= 1

        # seed a completion on a known id DIRECTLY on a replica: the
        # dedup window is server-level state, untouched by routers
        rep = f.replicas[0]
        rep_url = "http://%s:%d/v1/models/lm:generate" % tuple(rep.addr)
        body = {"prompt": [2, 3, 4], "max_new_tokens": 4}
        st, first = _post(rep_url, body,
                          headers={"X-TFOS-Request-Id": "req-pr19"})
        assert st == 200
        prefills = rep.engine.counters.snapshot()["counts"]["prefills"]

        sb = fleet.RouterStandby(f, probe_interval=0.1, confirm=3)
        sb.start()
        try:
            chaos.arm("kill_router_at_request=2,only=lm")
            # drive dispatches until the kill lands; the in-flight
            # request dies WITH the leader (connection reset) — that
            # one client retries after takeover, like any real client
            pending = []
            for i in range(2):
                try:
                    st, _ = _post(url, {"prompt": [1 + i, 2, 3],
                                        "max_new_tokens": 2})
                    assert st == 200
                except Exception:  # noqa: BLE001 - retried below
                    pending.append(i)
            assert sb.took_over.wait(timeout=30), \
                "standby never took over"
            assert f.control_epoch > old_epoch
            assert f.router is not old_router
            # bounded takeover window: the promoted router serves
            new_url = f.url("/v1/models/lm:generate")
            deadline = time.monotonic() + 15
            served = False
            while time.monotonic() < deadline and not served:
                try:
                    st, _ = _post(new_url, {"prompt": [5, 2, 3],
                                            "max_new_tokens": 2},
                                  timeout=30)
                    served = st == 200
                except Exception:  # noqa: BLE001 - until deadline
                    time.sleep(0.1)
            assert served, "promoted router never served"
            for i in pending:  # the killed request's retry completes
                st, _ = _post(new_url, {"prompt": [1 + i, 2, 3],
                                        "max_new_tokens": 2})
                assert st == 200
            # no request can be served by BOTH: old listener is dead
            with pytest.raises(OSError):
                _post("http://%s:%d/v1/models/lm:generate"
                      % tuple(old_addr),
                      {"prompt": [1], "max_new_tokens": 1}, timeout=5)
            # the deposed leader's late admin write: 409 ControlFenced
            st, resp = _post(
                "http://%s:%d/admin/ship_fence" % tuple(rep.addr),
                {"replica_id": "x", "min_epoch": 1},
                headers={"X-TFOS-Control-Epoch": str(old_epoch)})
            assert st == 409 and resp["kind"] == "ControlFenced", \
                (st, resp)
            assert resp["control_epoch"] == f.control_epoch
            # ...while the NEW leader's stamp is admitted
            st, _ = _post(
                "http://%s:%d/admin/ship_fence" % tuple(rep.addr),
                {"replica_id": "x", "min_epoch": 1},
                headers={"X-TFOS-Control-Epoch": str(f.control_epoch)})
            assert st == 200
            # takeover observability: counted on the standby's family
            assert sb.counters.snapshot()["counts"]["takeovers"] == 1
            # dedup survived the router swap: same id -> REPLAY of the
            # original completion, zero duplicate execution
            st, again = _post(rep_url, body,
                              headers={"X-TFOS-Request-Id": "req-pr19"})
            assert st == 200
            assert again == first, "replay must be the ORIGINAL result"
            assert rep.engine.counters.snapshot()["counts"][
                "prefills"] == prefills, \
                "duplicate completion after router death"
        finally:
            sb.stop()
