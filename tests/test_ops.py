"""Pallas kernel tests (interpreter mode on CPU; real compile on TPU)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax():
    import jax
    return jax


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(jax, causal):
    from tensorflowonspark_tpu.ops import flash_attention
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention)

    B, S, N, D = 2, 128, 2, 32
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)

    want = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          force_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grad(jax, causal):
    """Fused dq/dk/dv kernels (interpret mode) vs XLA reference grads.

    Rectangular blocks (32x16) exercise the BQ != BK tiling in both
    backward kernels; a non-trivial cotangent exercises delta."""
    from tensorflowonspark_tpu.ops import flash_attention
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention)

    B, S, N, D = 1, 64, 2, 16
    rng = np.random.RandomState(1)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)
    w = rng.randn(B, S, N, D).astype(np.float32)  # cotangent weights

    def loss_flash(q, k, v):
        return (w * flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=16,
            force_pallas=True, interpret=True)).sum()

    def loss_ref(q, k, v):
        return (w * reference_attention(q, k, v, causal=causal)).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-3)


def test_flash_attention_grad_bf16(jax):
    """bf16 inputs: fused backward keeps f32 stats/accumulators."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu.ops import flash_attention
    from tensorflowonspark_tpu.parallel.ring_attention import (
        reference_attention)

    B, S, N, D = 1, 64, 1, 16
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                               force_pallas=True, interpret=True) \
            .astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True) \
            .astype(jnp.float32).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gr, np.float32),
            rtol=0.1, atol=0.1)


def test_flash_attention_cpu_fallback(jax):
    """Without force_pallas on CPU, the XLA reference path serves."""
    from tensorflowonspark_tpu.ops import flash_attention

    x = np.ones((1, 16, 1, 8), np.float32)
    out = flash_attention(x, x, x)
    assert out.shape == x.shape


def test_flash_attention_key_mask(jax):
    """Padding mask parity (fwd + grads) vs the masked XLA reference."""
    from tensorflowonspark_tpu.ops.flash_attention import (
        _reference_lse, flash_attention)

    B, S, N, D = 2, 64, 2, 16
    rng = np.random.RandomState(7)
    q = rng.randn(B, S, N, D).astype(np.float32)
    k = rng.randn(B, S, N, D).astype(np.float32)
    v = rng.randn(B, S, N, D).astype(np.float32)
    mask = np.ones((B, S), bool)
    mask[0, 40:] = False  # padded tail
    mask[1, 10:20] = False  # hole in the middle

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, key_mask=mask, block_q=32,
                               block_k=16, force_pallas=True,
                               interpret=True).sum()

    def loss_ref(q, k, v):
        import jax.numpy as jnp
        bias = jnp.where(mask, 0.0, -jnp.inf).astype(jnp.float32)
        out, _ = _reference_lse(q, k, v, False, D ** -0.5, bias)
        return out.sum()

    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, key_mask=mask, block_q=32,
                                   block_k=16, force_pallas=True,
                                   interpret=True)),
        np.asarray(flash_attention(q, k, v, key_mask=mask)),  # XLA ref
        rtol=2e-4, atol=2e-4)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-3)


def test_flash_attention_cross_lengths(jax):
    """Rectangular (cross) attention: S_q != S_kv."""
    from tensorflowonspark_tpu.ops.flash_attention import (
        _reference_lse, flash_attention)

    B, Sq, Sk, N, D = 1, 32, 64, 2, 16
    rng = np.random.RandomState(8)
    q = rng.randn(B, Sq, N, D).astype(np.float32)
    k = rng.randn(B, Sk, N, D).astype(np.float32)
    v = rng.randn(B, Sk, N, D).astype(np.float32)

    got = flash_attention(q, k, v, block_q=16, block_k=32,
                          force_pallas=True, interpret=True)
    want, _ = _reference_lse(q, k, v, False, D ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
