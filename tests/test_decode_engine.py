"""Continuous-batching decode engine invariants (serving.DecodeEngine).

The engine's whole contract is that slot-structured continuous batching
is INVISIBLE to each request: at temperature=0 a request's output must
be bitwise-identical to a solo ``generation.generate`` call, regardless
of what the other slots are doing, how often its slot was previously
occupied, or which shape bucket its prompt padded into. Plus the perf
contract that motivates the design: compile count stays O(buckets),
not O(request signatures).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import generation, serving
from tensorflowonspark_tpu.models.decoder import DecoderLM

V, H, NH, L, MAXLEN = 17, 32, 4, 2, 48


@pytest.fixture(scope="module")
def lm():
    train = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                      max_len=MAXLEN, decode=False)
    dec = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                    max_len=MAXLEN, decode=True)
    params = train.init(jax.random.PRNGKey(7),
                        jnp.zeros((2, MAXLEN), jnp.int32))["params"]
    return dec, params


def _solo(dec, params, prompt, max_new, **kw):
    out = generation.generate_jit(
        dec, params, jnp.asarray([prompt], jnp.int32), max_new, **kw)
    return np.asarray(out)[0].tolist()


def _mixed_requests(rng, n, lo_p=3, hi_p=12, lo_n=1, hi_n=10):
    reqs = []
    for _ in range(n):
        p = rng.randint(0, V, size=rng.randint(lo_p, hi_p)).tolist()
        mn = int(rng.randint(lo_n, hi_n))
        reqs.append((p, min(mn, MAXLEN - len(p))))
    return reqs


def test_temp0_bitwise_identical_to_solo_generate(lm):
    """The acceptance pin: mixed-length requests through a shared
    2-slot engine emit EXACTLY the tokens each would get alone."""
    dec, params = lm
    reqs = _mixed_requests(np.random.RandomState(0), 6)
    want = [_solo(dec, params, p, mn) for p, mn in reqs]
    with serving.DecodeEngine(dec, params, slots=2) as eng:
        handles = [eng.submit(p, mn) for p, mn in reqs]
        got = [h.result(300) for h in handles]
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, (i, g, w)


def test_no_cross_slot_logit_leakage(lm):
    """A request's tokens must not change with slot COMPANY: run one
    request alone (its neighbor slot idle/masked), then crowded among
    five concurrent others — identical output both times, so neither
    idle slots nor foreign active sequences perturb its logits."""
    dec, params = lm
    rng = np.random.RandomState(1)
    probe = (rng.randint(0, V, size=7).tolist(), 9)
    others = _mixed_requests(rng, 5)
    with serving.DecodeEngine(dec, params, slots=2) as eng:
        alone = eng.submit(*probe).result(300)
    with serving.DecodeEngine(dec, params, slots=2) as eng:
        hs = [eng.submit(p, mn) for p, mn in others[:2]]
        hp = eng.submit(*probe)
        hs += [eng.submit(p, mn) for p, mn in others[2:]]
        crowded = hp.result(300)
        for h in hs:
            h.result(300)
    assert alone == crowded


def test_slot_reuse_after_eos_has_no_cache_bleed(lm):
    """A 1-slot engine forces every request through the SAME slot, each
    admission overwriting the previous occupant's cache rows; with an
    eos that fires mid-sequence the slot frees early and the next
    request must still match its solo rollout bitwise."""
    dec, params = lm
    rng = np.random.RandomState(2)
    # choose as eos a token the greedy rollout actually emits, so the
    # early-exit path (slot freed before max_new) really executes
    first = rng.randint(0, V, size=5).tolist()
    base = _solo(dec, params, first, 10)
    eos = base[len(first) + 1]
    reqs = [(first, 10)] + _mixed_requests(rng, 4)
    want = []
    for p, mn in reqs:
        solo = _solo(dec, params, p, mn, eos_token=eos)
        gen = solo[len(p):]
        if eos in gen:  # engine semantics: truncate at (and keep) eos
            gen = gen[:gen.index(eos) + 1]
        want.append(p + gen)
    with serving.DecodeEngine(dec, params, slots=1, eos_token=eos) as eng:
        got = [eng.submit(p, mn).result(300) for p, mn in reqs]
    assert got == want
    # the eos path genuinely fired early on the seeded first request
    assert got[0][-1] == eos and len(got[0]) < len(first) + 10


def test_compile_count_bounded_by_buckets(lm):
    """The perf contract: a workload of many DISTINCT (prompt_len,
    max_new) signatures compiles one decode program per engine config
    plus at most one prefill program per touched bucket — while the
    old whole-generation path would compile once per signature."""
    # a dedicated model config so generation.slot_step_fns' lru cache
    # entry (and its program counts) belongs to this test alone
    train = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=1,
                      max_len=64, decode=False)
    dec = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=1,
                    max_len=64, decode=True)
    params = train.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 64), jnp.int32))["params"]
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, V, size=n).tolist(), int(rng.randint(1, 9)))
            for n in (2, 3, 5, 7, 9, 12, 17, 21, 29, 33)]
    signatures = {(len(p), mn) for p, mn in reqs}
    assert len(signatures) == len(reqs)  # genuinely mixed workload
    with serving.DecodeEngine(dec, params, slots=4) as eng:
        buckets = eng.buckets
        touched = {generation.bucket_for(len(p), buckets)
                   for p, mn in reqs}
        for h in [eng.submit(p, mn) for p, mn in reqs]:
            h.result(300)
        stats = eng.compile_stats()
    assert stats["decode_programs"] == 1, stats
    assert stats["prefill_programs"] == len(touched), (stats, touched)
    assert stats["prefill_programs"] <= len(buckets)


def test_max_new_one_and_zero_paths(lm):
    """max_new=1 completes at prefill (no decode step); max_new=0 never
    touches the device and returns the prompt."""
    dec, params = lm
    prompt = [1, 2, 3, 4]
    want = _solo(dec, params, prompt, 1)
    with serving.DecodeEngine(dec, params, slots=2) as eng:
        h1 = eng.submit(prompt, 1)
        h0 = eng.submit(prompt, 0)
        assert h1.result(300) == want
        assert h0.result(300) == prompt
        snap = eng.counters.snapshot()["counts"]
    assert snap.get("decode_steps", 0) == 0, snap
    assert snap["prefills"] == 1, snap


def test_submit_validation(lm):
    dec, params = lm
    with serving.DecodeEngine(dec, params, slots=1,
                              total_len=32) as eng:
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2], -1)
        with pytest.raises(ValueError, match="bucket"):
            eng.submit([1] * 33, 1)
        with pytest.raises(ValueError, match="vocab"):
            eng.submit([1, 99999], 1)
        with pytest.raises(ValueError, match="vocab"):
            eng.submit([-5], 1)
        with pytest.raises(ValueError, match="total_len"):
            eng.submit([1] * 30, 8)
    with pytest.raises(RuntimeError, match="stopped"):
        eng.submit([1], 1)
    # the degenerate max_new=0 path must hit the same liveness checks:
    # a dead engine answering a probe with success reads as healthy
    with pytest.raises(RuntimeError, match="stopped"):
        eng.submit([1], 0)


def test_engine_rejects_bad_sampling_config(lm):
    """The engine shares generate()'s sampling checks: a config that
    would serve silently wrong tokens must refuse at construction."""
    dec, params = lm
    with pytest.raises(ValueError, match="top_k"):
        serving.DecodeEngine(dec, params, slots=1, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        serving.DecodeEngine(dec, params, slots=1, top_p=0.0)
    with pytest.raises(ValueError, match="PRNG"):
        serving.DecodeEngine(dec, params, slots=1, temperature=0.8)


def test_queue_full_backpressure(lm):
    """submit() past max_queue raises QueueFull with nothing queued —
    and a multi-request body is all-or-nothing."""
    dec, params = lm
    with serving.DecodeEngine(dec, params, slots=1, max_queue=2) as eng:
        blocker = eng.submit([1, 2], 40)  # holds the single slot
        deadline = time.monotonic() + 60
        while eng.counters.snapshot()["counts"].get("prefills", 0) < 1:
            assert time.monotonic() < deadline, "blocker never admitted"
            time.sleep(0.01)
        eng.submit([1], 4)
        eng.submit([2], 4)  # queue now at max_queue=2
        with pytest.raises(serving.QueueFull, match="max_queue"):
            eng.submit([3], 4)
        # atomic body admission: 2 queued + 2 more > max_queue, so the
        # WHOLE body refuses and queue_depth is unchanged
        depth_before = eng.counters.snapshot()["gauges"]["queue_depth"]
        with pytest.raises(serving.QueueFull):
            eng._submit_many([([4], 4), ([5], 4)])
        depth = eng.counters.snapshot()["gauges"]["queue_depth"]
        assert depth == depth_before
        blocker.result(300)  # drain so stop() isn't racing live decode


def test_streaming_and_counters(lm):
    """stream() yields tokens incrementally; the tracing.Counters
    export (queue depth / slot occupancy / tokens-per-step) reflects
    the run."""
    dec, params = lm
    prompt = [3, 1, 4, 1]
    want = _solo(dec, params, prompt, 8)
    with serving.DecodeEngine(dec, params, slots=2) as eng:
        h = eng.submit(prompt, 8)
        streamed = list(h.stream(timeout=300))
        snap = eng.counters.snapshot()
        tps = eng.counters.rate("decode_tokens", "decode_steps")
    assert prompt + streamed == want
    assert h.latency is not None and h.latency >= 0
    assert snap["counts"]["tokens"] == 8
    # the prefill-emitted first token is counted in "tokens" but NOT in
    # "decode_tokens", so occupancy stays bounded by the slot count
    assert snap["counts"]["decode_tokens"] == 7
    assert snap["counts"]["requests_completed"] == 1
    assert snap["gauges"]["queue_depth"] == 0
    assert 0 < tps <= eng.slots


def test_engine_failure_fails_clients_not_hangs(lm):
    """A scheduler-loop death must surface to every waiting client as
    an error, and later submits must refuse loudly."""
    dec, params = lm
    eng = serving.DecodeEngine(dec, params, slots=2)
    try:
        # poison the loop: a params pytree of the wrong structure makes
        # the prefill call raise inside the scheduler thread
        eng.params = {"nope": jnp.zeros(())}
        h = eng.submit([1, 2, 3], 4)
        with pytest.raises(RuntimeError, match="failed"):
            h.result(120)
        with pytest.raises(RuntimeError):
            eng.submit([1, 2, 3], 4)
    finally:
        eng.stop()
