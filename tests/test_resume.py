"""Cluster-level checkpoint/resume: the reference's recovery story.

SURVEY.md §5 "Checkpoint / resume": recovery in the reference is
resubmit-the-job + restore-latest from shared storage. Here: a first
cluster.run trains and checkpoints (chief-only commit), a SECOND
cluster.run — a fresh cluster id, fresh trainer processes — restores
the latest step and continues from it. Proves the orbax round trip
through real trainer process boundaries, not just in-process.
"""

import json
import os
import sys

import cloudpickle

from tensorflowonspark_tpu import cluster
from tensorflowonspark_tpu.engine import Context

# Executor processes cannot import this test module, so its functions
# must ship by value (the engine's cloudpickle serializer honors this).
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _train_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import checkpoint, training
    from tensorflowonspark_tpu.models.lenet import LeNet

    devices = ctx.initialize_jax()
    mesh = ctx.mesh({"data": len(devices)})
    trainer = training.Trainer(LeNet(num_classes=10),
                               optax.sgd(0.01), mesh)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 28, 28, 1).astype(np.float32)
    y = (np.arange(16) % 10).astype(np.int64)
    state = trainer.init(jax.random.PRNGKey(0), x)

    ckpt = checkpoint.Checkpointer(args["dir"],
                                   chief=ctx.job_name == "chief")
    restored = ckpt.restore(state)
    start_step = 0 if restored is None else int(restored["step"])
    if restored is not None:
        state = restored
    for _ in range(args["steps"]):
        state, metrics = trainer.step(state, {"x": x, "y": y})
    jax.block_until_ready(metrics["loss"])
    ckpt.save(int(state["step"]), state, force=True)
    ckpt.wait()
    ckpt.close()
    with open(os.path.join(args["dir"], "run-%d.json" % args["run"]),
              "w") as f:
        json.dump({"start_step": start_step,
                   "end_step": int(state["step"]),
                   "loss": float(metrics["loss"])}, f)


def test_cluster_resume_from_checkpoint(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    os.makedirs(ckpt_dir)

    for run in (1, 2):
        sc = Context(num_executors=1,
                     work_root=str(tmp_path / ("engine%d" % run)))
        try:
            tfc = cluster.run(sc, _train_fun,
                              {"dir": ckpt_dir, "steps": 3, "run": run},
                              num_executors=1,
                              input_mode=cluster.InputMode.TENSORFLOW)
            tfc.shutdown()
        finally:
            sc.stop()

    r1 = json.load(open(os.path.join(ckpt_dir, "run-1.json")))
    r2 = json.load(open(os.path.join(ckpt_dir, "run-2.json")))
    assert r1["start_step"] == 0 and r1["end_step"] == 3
    # the resubmitted job restored step 3 and continued to 6
    assert r2["start_step"] == 3, r2
    assert r2["end_step"] == 6, r2
