"""Cluster-level checkpoint/resume: the reference's recovery story.

SURVEY.md §5 "Checkpoint / resume": recovery in the reference is
resubmit-the-job + restore-latest from shared storage. Here: a first
cluster.run trains and checkpoints (chief-only commit), a SECOND
cluster.run — a fresh cluster id, fresh trainer processes — restores
the latest step and continues from it. Proves the orbax round trip
through real trainer process boundaries, not just in-process.
"""

import json
import os
import sys

import cloudpickle

from tensorflowonspark_tpu import cluster
from tensorflowonspark_tpu.engine import Context

# Executor processes cannot import this test module, so its functions
# must ship by value (the engine's cloudpickle serializer honors this).
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _train_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import checkpoint, training
    from tensorflowonspark_tpu.models.lenet import LeNet

    devices = ctx.initialize_jax()
    mesh = ctx.mesh({"data": len(devices)})
    trainer = training.Trainer(LeNet(num_classes=10),
                               optax.sgd(0.01), mesh)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 28, 28, 1).astype(np.float32)
    y = (np.arange(16) % 10).astype(np.int64)
    state = trainer.init(jax.random.PRNGKey(0), x)

    ckpt = checkpoint.Checkpointer(args["dir"],
                                   chief=ctx.job_name == "chief")
    restored = ckpt.restore(state)
    start_step = 0 if restored is None else int(restored["step"])
    if restored is not None:
        state = restored
    for _ in range(args["steps"]):
        state, metrics = trainer.step(state, {"x": x, "y": y})
    jax.block_until_ready(metrics["loss"])
    ckpt.save(int(state["step"]), state, force=True)
    ckpt.wait()
    ckpt.close()
    with open(os.path.join(args["dir"], "run-%d.json" % args["run"]),
              "w") as f:
        json.dump({"start_step": start_step,
                   "end_step": int(state["step"]),
                   "loss": float(metrics["loss"])}, f)


def _run_twice(train_fun, tmp_path, prefix):
    """Train-save, then resubmit-restore-train in a FRESH cluster; return
    the two runs' handshake dicts (train_fun writes '<prefix>run-N.json')."""
    ckpt_dir = str(tmp_path / (prefix + "ckpt"))
    os.makedirs(ckpt_dir)
    for run in (1, 2):
        sc = Context(num_executors=1,
                     work_root=str(tmp_path / ("%sengine%d" % (prefix, run))))
        try:
            tfc = cluster.run(sc, train_fun,
                              {"dir": ckpt_dir, "steps": 3, "run": run},
                              num_executors=1,
                              input_mode=cluster.InputMode.TENSORFLOW)
            tfc.shutdown()
        finally:
            sc.stop()
    return tuple(
        json.load(open(os.path.join(ckpt_dir, "%srun-%d.json" % (prefix, n))))
        for n in (1, 2))


def test_cluster_resume_from_checkpoint(tmp_path):
    r1, r2 = _run_twice(_train_fun, tmp_path, "")
    assert r1["start_step"] == 0 and r1["end_step"] == 3
    # the resubmitted job restored step 3 and continued to 6
    assert r2["start_step"] == 3, r2
    assert r2["end_step"] == 6, r2


def _tp_train_fun(args, ctx):
    import jax
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu import checkpoint, training
    from tensorflowonspark_tpu.parallel.sharding import tree_shardings

    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16, name="up")(x))
            return nn.Dense(8, name="down")(x)

    devices = ctx.initialize_jax()
    mesh = ctx.mesh({"data": len(devices) // 2, "model": 2})
    rules = (("up/kernel", P(None, "model")),
             ("down/kernel", P("model", None)))
    trainer = training.Trainer(MLP(), optax.sgd(0.05), mesh,
                               constrain_state=False, donate_state=False)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 12).astype(np.float32)
    y = (np.arange(16) % 8).astype(np.int64)
    state = trainer.init(jax.random.PRNGKey(0), x)
    shardings = tree_shardings(state["params"], mesh, rules, default=P())
    state["params"] = jax.device_put(state["params"], shardings)

    ckpt = checkpoint.Checkpointer(args["dir"],
                                   chief=ctx.job_name == "chief")
    restored = ckpt.restore(state)
    start_step = 0 if restored is None else int(restored["step"])
    if restored is not None:
        state = restored
        # the restore must come back in the TP layout state carries
        up = state["params"]["up"]["kernel"]
        assert up.sharding.spec == P(None, "model"), up.sharding
    batch = jax.device_put({"x": x, "y": y}, trainer.batch_sharding)
    for _ in range(args["steps"]):
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics["loss"])
    ckpt.save(int(state["step"]), state, force=True)
    ckpt.wait()
    ckpt.close()
    with open(os.path.join(args["dir"], "tp-run-%d.json" % args["run"]),
              "w") as f:
        json.dump({"start_step": start_step,
                   "end_step": int(state["step"]),
                   "loss": float(metrics["loss"])}, f)


def test_cluster_resume_tp_sharded_state(tmp_path):
    """Resubmit-and-restore with a TENSOR-PARALLEL state: the checkpoint
    round-trips through fresh cluster processes with the sharded layout
    preserved (SURVEY.md §5 checkpoint/resume; r3 VERDICT task 5 at
    cluster level)."""
    r1, r2 = _run_twice(_tp_train_fun, tmp_path, "tp-")
    assert r1["start_step"] == 0 and r1["end_step"] == 3
    assert r2["start_step"] == 3 and r2["end_step"] == 6
    assert r2["loss"] < r1["loss"]  # training actually continued
