"""KV-cache decode correctness: cached generation == full forward.

The whole value of the cache is that it must be INVISIBLE: one-token
cached steps have to reproduce the full causal forward exactly, and
greedy generation must equal the naive re-run-the-prefix rollout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import generation
from tensorflowonspark_tpu.models.decoder import DecoderLM

V, H, NH, L, MAXLEN = 17, 32, 4, 2, 32


@pytest.fixture(scope="module")
def lm():
    train_model = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                            max_len=MAXLEN, decode=False)
    decode_model = DecoderLM(vocab=V, hidden=H, num_heads=NH, num_layers=L,
                             max_len=MAXLEN, decode=True)
    tokens = jnp.zeros((2, MAXLEN), jnp.int32)
    params = train_model.init(jax.random.PRNGKey(7), tokens)["params"]
    return train_model, decode_model, params


def test_cached_steps_match_full_forward(lm):
    train_model, decode_model, params = lm
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, V, size=(2, 12)), jnp.int32)

    full = train_model.apply({"params": params}, tokens)  # [B, S, V]

    cache = generation.init_cache(decode_model, 2, MAXLEN)
    stepped = []
    for i in range(tokens.shape[1]):
        logits, updated = decode_model.apply(
            {"params": params, "cache": cache}, tokens[:, i:i + 1],
            mutable=["cache"])
        cache = updated["cache"]
        stepped.append(logits[:, 0, :])
    stepped = jnp.stack(stepped, axis=1)

    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_greedy_generate_matches_naive_rollout(lm):
    train_model, decode_model, params = lm
    rng = np.random.RandomState(1)
    prompt = jnp.asarray(rng.randint(0, V, size=(2, 5)), jnp.int32)
    new = 6

    got = generation.generate(decode_model, params, prompt, new)
    assert got.shape == (2, 5 + new)
    np.testing.assert_array_equal(np.asarray(got[:, :5]),
                                  np.asarray(prompt))

    # naive rollout: re-run the full prefix every step, take argmax
    seq = prompt
    for _ in range(new):
        logits = train_model.apply({"params": params}, seq)
        seq = jnp.concatenate(
            [seq, jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)],
            axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_generate_jit_compiles_once_and_matches(lm):
    _, decode_model, params = lm
    prompt = jnp.ones((1, 4), jnp.int32)
    eager = generation.generate(decode_model, params, prompt, 3)
    jitted = generation.generate_jit(decode_model, params, prompt, 3)
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))


def test_temperature_sampling_deterministic_per_key(lm):
    _, decode_model, params = lm
    prompt = jnp.ones((2, 3), jnp.int32)
    key = jax.random.PRNGKey(3)
    a = generation.generate(decode_model, params, prompt, 5,
                            temperature=0.8, rng=key)
    b = generation.generate(decode_model, params, prompt, 5,
                            temperature=0.8, rng=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 8)
    with pytest.raises(ValueError, match="PRNG"):
        generation.generate(decode_model, params, prompt, 2, temperature=1.0,
                            rng=None)


def test_zero_new_tokens_returns_prompt(lm):
    # max_new_tokens=0 used to crash in jax.random.split(rng, 0); the
    # contract ([B, S + N]) degenerates to the prompt itself
    _, decode_model, params = lm
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    got = generation.generate(decode_model, params, prompt, 0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(prompt))
    with pytest.raises(ValueError, match="max_new_tokens"):
        generation.generate(decode_model, params, prompt, -1)


def test_generate_rejects_overlong(lm):
    _, decode_model, params = lm
    prompt = jnp.ones((1, MAXLEN - 1), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generation.generate(decode_model, params, prompt, 2)


def test_tp_sharded_decode_matches_replicated(lm):
    """Generation with megatron-sharded params (DECODER_TP_RULES) emits
    byte-identical tokens: the KV cache inherits the head sharding and
    the decode loop needs no code changes for tensor parallelism."""
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel import build_mesh
    from tensorflowonspark_tpu.parallel.sharding import (
        DECODER_TP_RULES, tree_shardings)

    _, decode_model, params = lm
    prompt = jnp.asarray(
        np.random.RandomState(5).randint(0, V, (2, 6)), jnp.int32)
    base = generation.generate(decode_model, params, prompt, 5)

    mesh = build_mesh({"data": 2, "model": 4})
    shardings = tree_shardings(params, mesh, DECODER_TP_RULES, default=P())
    sparams = jax.device_put(params, shardings)
    # the qkv kernels must actually be sharded, not silently replicated
    qk = sparams["block_0"]["attn"]["query"]["kernel"]
    assert qk.sharding.spec == P(None, "model", None), qk.sharding
    with mesh:
        tp = generation.generate(decode_model, sparams, prompt, 5)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tp))


def test_top_k_restricts_to_greedy_at_k1(lm):
    """top_k=1 with any temperature must equal greedy decoding."""
    _, decode_model, params = lm
    prompt = jnp.ones((2, 4), jnp.int32)
    greedy = generation.generate(decode_model, params, prompt, 5)
    k1 = generation.generate(decode_model, params, prompt, 5,
                             temperature=1.5, rng=jax.random.PRNGKey(9),
                             top_k=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))


def test_eos_freezes_sequences(lm):
    """After eos, a sequence emits pad_token for every later position,
    while other sequences keep generating (static shapes throughout)."""
    _, decode_model, params = lm
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, V, (3, 4)), jnp.int32)
    base = generation.generate(decode_model, params, prompt, 8)
    # choose as "eos" a token the greedy rollout actually emits early
    gen_part = np.asarray(base[:, 4:])
    eos = int(gen_part[0, 1])
    out = np.asarray(generation.generate(
        decode_model, params, prompt, 8, eos_token=eos, pad_token=7))
    for row in out[:, 4:]:
        hits = np.where(row == eos)[0]
        if hits.size:
            after = row[hits[0] + 1:]
            assert np.all(after == 7), (row, eos)
    # the frozen run matches the base rollout UP TO each eos position
    for brow, frow in zip(gen_part, out[:, 4:]):
        hits = np.where(frow == eos)[0]
        upto = hits[0] + 1 if hits.size else len(frow)
        np.testing.assert_array_equal(brow[:upto], frow[:upto])


def test_top_p_bounds_and_degenerate_cases(lm):
    """top_p=1.0 equals unrestricted sampling (same key); a tiny top_p
    keeps only the argmax, i.e. equals greedy."""
    _, decode_model, params = lm
    prompt = jnp.ones((2, 4), jnp.int32)
    key = jax.random.PRNGKey(11)

    full = generation.generate(decode_model, params, prompt, 5,
                               temperature=1.0, rng=key)
    p1 = generation.generate(decode_model, params, prompt, 5,
                             temperature=1.0, rng=key, top_p=1.0)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(p1))

    greedy = generation.generate(decode_model, params, prompt, 5)
    tiny = generation.generate(decode_model, params, prompt, 5,
                               temperature=2.0, rng=key, top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(tiny))

    with pytest.raises(ValueError, match="top_p"):
        generation.generate(decode_model, params, prompt, 2,
                            temperature=1.0, rng=key, top_p=0.0)


def test_filters_are_index_based_on_ties(lm):
    """Uniform logits must NOT defeat the filters: top_k=1/tiny top_p on
    an all-equal distribution still restrict to a single index (a value
    threshold would keep the whole vocabulary). Exercises the SHIPPED
    filter_logits, not a copy."""
    _, decode_model, params = lm
    uniform = jnp.zeros((2, V))
    key = jax.random.PRNGKey(13)

    def run_pick(top_k=None, top_p=None):
        filtered = generation.filter_logits(uniform, top_k=top_k,
                                            top_p=top_p)
        return int(jnp.sum(jnp.isfinite(filtered[0])))

    assert run_pick(top_k=1) == 1
    assert run_pick(top_p=1e-6) == 1
    # uniform mass 1/V per token: nucleus keeps mass-before < p, i.e.
    # floor(p*V) + 1 tokens
    assert run_pick(top_p=0.5) == int(0.5 * V) + 1
    # and end-to-end: samples with top_k=1 on the real model stay greedy
    greedy = generation.generate(decode_model, params,
                                 jnp.ones((1, 3), jnp.int32), 4)
    k1 = generation.generate(decode_model, params,
                             jnp.ones((1, 3), jnp.int32), 4,
                             temperature=3.0, rng=key, top_k=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))


def test_flash_branch_matches_reference_at_block_multiple():
    """Train-mode attention takes the flash branch when S % 128 == 0;
    its output must equal the XLA reference (which shorter sequences
    use), so branch selection is semantics-free."""
    import importlib

    fa = importlib.import_module("tensorflowonspark_tpu.ops.flash_attention")
    model = DecoderLM(vocab=11, hidden=32, num_heads=4, num_layers=1,
                      max_len=128, decode=False)
    tokens = jnp.asarray(
        np.random.RandomState(9).randint(0, 11, (1, 128)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)

    # recompute attention by hand through the reference for layer 0 and
    # check the model's logits are finite + causal: position 0's logits
    # must not change when later tokens change
    tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % 11)
    full2 = model.apply({"params": params}, tokens2)
    np.testing.assert_allclose(np.asarray(full[:, :-1]),
                               np.asarray(full2[:, :-1]),
                               rtol=1e-5, atol=1e-5)
    assert np.isfinite(np.asarray(full)).all()


def test_multi_token_chunked_decode_matches_full_forward(lm):
    """A multi-token decode call CONTINUES from the cache cursor (fused
    chunked prefill) — it must match the full causal forward, and a
    second chunk after the first must not restart at position 0 (the
    silent-clobber regression the old position-0 assumption invited)."""
    train_model, decode_model, params = lm
    rng = np.random.RandomState(4)
    tokens = jnp.asarray(rng.randint(0, V, size=(2, 12)), jnp.int32)
    full = train_model.apply({"params": params}, tokens)  # [B, S, V]

    cache = generation.init_cache(decode_model, 2, MAXLEN)
    logits1, upd = decode_model.apply(
        {"params": params, "cache": cache}, tokens[:, :5],
        mutable=["cache"])
    logits2, upd = decode_model.apply(
        {"params": params, "cache": upd["cache"]}, tokens[:, 5:],
        mutable=["cache"])
    got = jnp.concatenate([logits1, logits2], axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               rtol=2e-4, atol=2e-4)
    # and the chunked path tracks one-token-at-a-time steps to float
    # noise (XLA's matmul accumulation varies with the row count, so
    # bitwise equality across CHUNKINGS is not contractual — the
    # engine's bitwise solo-parity is pinned separately, per config, in
    # tests/test_decode_engine.py)
    cache = generation.init_cache(decode_model, 2, MAXLEN)
    stepped = []
    for i in range(tokens.shape[1]):
        step_logits, upd_s = decode_model.apply(
            {"params": params, "cache": cache}, tokens[:, i:i + 1],
            mutable=["cache"])
        cache = upd_s["cache"]
        stepped.append(step_logits[:, 0, :])
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(jnp.stack(stepped, axis=1)),
                               rtol=1e-4, atol=1e-5)
