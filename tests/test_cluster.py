"""End-to-end cluster micro-jobs.

Reference test strategy (SURVEY.md §4 ``tests/test_TFCluster.py``): run the
full bootstrap on a real multi-process engine on one host, with trivial
map_funs — a sum-the-fed-numbers trainer, a SPARK-mode train + inference
round-trip, an inline TENSORFLOW-mode run, and shutdown error propagation.
"""

import json
import os

import pytest

from tensorflowonspark_tpu import cluster
from tensorflowonspark_tpu.engine import Context


@pytest.fixture()
def sc(tmp_path):
    ctx = Context(num_executors=2, work_root=str(tmp_path / "engine"))
    yield ctx
    ctx.stop()


def test_spark_mode_train_roundtrip(sc, tmp_path):
    """Queue-fed training: each node sums what it is fed; totals add up."""
    out_dir = str(tmp_path / "sums")
    os.makedirs(out_dir)

    def map_fun(args, ctx):
        feed = ctx.get_data_feed(train_mode=True)
        total = 0
        count = 0
        while not feed.should_stop():
            batch = feed.next_batch(8)
            total += sum(batch)
            count += len(batch)
        with open(os.path.join(args["out_dir"],
                               "node-{}.json".format(ctx.executor_id)), "w") as f:
            json.dump({"total": total, "count": count,
                       "job_name": ctx.job_name,
                       "task_index": ctx.task_index,
                       "num_workers": ctx.num_workers}, f)

    tfc = cluster.run(sc, map_fun, {"out_dir": out_dir}, num_executors=2,
                      input_mode=cluster.InputMode.SPARK)
    assert len(tfc.cluster_info) == 2
    data = sc.parallelize(range(100), 4)
    tfc.train(data, num_epochs=2)
    tfc.shutdown()

    files = sorted(os.listdir(out_dir))
    assert len(files) == 2
    stats = [json.load(open(os.path.join(out_dir, f))) for f in files]
    assert sum(s["total"] for s in stats) == sum(range(100)) * 2
    assert sum(s["count"] for s in stats) == 200
    assert sorted(s["job_name"] for s in stats) == ["chief", "worker"]
    assert all(s["num_workers"] == 2 for s in stats)


def test_spark_mode_inference_roundtrip(sc):
    """Inference: every record comes back transformed, count preserved."""

    def map_fun(args, ctx):
        feed = ctx.get_data_feed(train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(8)
            if batch:
                feed.batch_results([x * 10 for x in batch])

    tfc = cluster.run(sc, map_fun, {}, num_executors=2,
                      input_mode=cluster.InputMode.SPARK)
    data = sc.parallelize(range(20), 4)
    results = tfc.inference(data).collect()
    # EXACT order, not a multiset: the reference guarantees per-partition
    # count/order (q_in.join() + counted q_out reads, SURVEY.md §7.3
    # names it a hard part), and collect() reassembles partitions in
    # order — so the round trip must be order-preserving end to end.
    assert results == [x * 10 for x in range(20)]
    tfc.shutdown()


def test_inference_deep_partition_no_wedge(sc):
    """Results drain concurrently with feeding (ADVICE r3): a partition
    deep enough to fill BOTH bounded queues (input 16 chunks x 256
    records, output 256 result items) must stream through instead of
    deadlocking trainer batch_results against feeder backpressure."""

    def map_fun(args, ctx):
        feed = ctx.get_data_feed(train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(8)
            if batch:
                feed.batch_results([x + 1 for x in batch])

    prev = os.environ.get("TFOS_FEED_TRANSPORT")
    os.environ["TFOS_FEED_TRANSPORT"] = "queue"
    try:
        tfc = cluster.run(sc, map_fun, {}, num_executors=2,
                          input_mode=cluster.InputMode.SPARK)
        n = 8000  # > 16*256 buffered input + > 256 buffered result lists
        data = sc.parallelize(range(n), 2)
        results = tfc.inference(data, feed_timeout=60).collect()
        assert len(results) == n
        # exact order even with both queues cycling through backpressure
        assert results == [x + 1 for x in range(n)]
        tfc.shutdown()
    finally:
        if prev is None:
            os.environ.pop("TFOS_FEED_TRANSPORT", None)
        else:
            os.environ["TFOS_FEED_TRANSPORT"] = prev


def test_tensorflow_mode_inline(sc, tmp_path):
    """InputMode.TENSORFLOW: fn runs inline; run() returns after barrier."""
    out_dir = str(tmp_path / "marks")
    os.makedirs(out_dir)

    def map_fun(args, ctx):
        with open(os.path.join(args["out_dir"],
                               "node-{}".format(ctx.executor_id)), "w") as f:
            f.write("{}:{}".format(ctx.job_name, ctx.task_index))

    tfc = cluster.run(sc, map_fun, {"out_dir": out_dir}, num_executors=2,
                      input_mode=cluster.InputMode.TENSORFLOW)
    tfc.shutdown()
    assert sorted(os.listdir(out_dir)) == ["node-0", "node-1"]


def test_spark_mode_error_propagates(sc):
    """A trainer exception must surface as a driver-side raise at shutdown."""

    def map_fun(args, ctx):
        feed = ctx.get_data_feed(train_mode=True)
        feed.next_batch(1)
        raise ValueError("boom on node {}".format(ctx.executor_id))

    tfc = cluster.run(sc, map_fun, {}, num_executors=2,
                      input_mode=cluster.InputMode.SPARK)
    data = sc.parallelize(range(10), 2)
    tfc.train(data)
    with pytest.raises(RuntimeError) as err:
        tfc.shutdown(grace_secs=1)
    assert "boom" in str(err.value.__cause__ or err.value)


def test_tensorflow_mode_error_propagates(sc):
    """Inline map_fun exception fails the bootstrap job -> shutdown raises."""

    def map_fun(args, ctx):
        if ctx.job_name == "worker":
            raise ValueError("inline boom")

    tfc = cluster.run(sc, map_fun, {}, num_executors=2,
                      input_mode=cluster.InputMode.TENSORFLOW)
    with pytest.raises(RuntimeError):
        tfc.shutdown()


def test_cluster_spec_shape(sc):
    """cluster_spec has the TF_CONFIG shape; tensorboard_url None if off."""
    seen = {}

    def map_fun(args, ctx):
        pass

    tfc = cluster.run(sc, map_fun, {}, num_executors=2,
                      input_mode=cluster.InputMode.TENSORFLOW)
    assert tfc.tensorboard_url() is None
    info = tfc.cluster_info
    assert [n["executor_id"] for n in info] == [0, 1]
    assert info[0]["job_name"] == "chief"
    assert info[1]["job_name"] == "worker"
    tfc.shutdown()


def test_ps_and_evaluator_roles(tmp_path):
    """Role-template parity: num_ps and eval_node create ps/evaluator
    nodes whose fns run with those job names, parked OUTSIDE the device
    collective (they are not participants)."""
    out = str(tmp_path / "roles")
    os.makedirs(out)

    def map_fun(args, ctx):
        participants = [n["job_name"] for n in ctx.participants()]
        with open(os.path.join(args["out"],
                               "role-%d" % ctx.executor_id), "w") as f:
            f.write("{}|{}".format(ctx.job_name, ",".join(participants)))

    sc = Context(num_executors=3, work_root=str(tmp_path / "engine"))
    try:
        tfc = cluster.run(sc, map_fun, {"out": out}, num_executors=3,
                          num_ps=1, eval_node=True,
                          input_mode=cluster.InputMode.TENSORFLOW)
        tfc.shutdown()
    finally:
        sc.stop()

    roles = {}
    for name in os.listdir(out):
        job, parts = open(os.path.join(out, name)).read().split("|")
        roles[job] = parts.split(",")
    assert set(roles) == {"ps", "chief", "evaluator"}
    # every node agrees: only the chief joins the device collective
    for parts in roles.values():
        assert parts == ["chief"], roles


def test_shutdown_grace_rearms_on_feed_progress(tmp_path):
    """A trainer slowly stepping through its buffered backlog outlives a
    grace window shorter than the drain, because the DataFeed heartbeat
    re-arms the no-progress deadline (round-5 on-chip find: the old hard
    join cap killed a live trainer whose steps ran ~4s over the tunnel).
    Chunks land in DataFeed._pending long before the last batch is
    served, so this exercises the no-queue-traffic drain phase."""
    out = str(tmp_path / "done.json")

    def map_fun(args, ctx):
        import time as _t
        feed = ctx.get_data_feed(train_mode=True)
        total = 0
        while not feed.should_stop():
            batch = feed.next_batch(4)
            total += sum(batch)
            _t.sleep(0.8)  # slow "step": full drain ~8s >> 4s grace
        # the file is the proof the trainer was NOT killed mid-drain
        with open(args["out"], "w") as f:
            json.dump({"total": total}, f)

    sc = Context(num_executors=1, work_root=str(tmp_path / "engine"))
    try:
        tfc = cluster.run(sc, map_fun, {"out": out}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        tfc.train(sc.parallelize(range(40), 1))
        tfc.shutdown(grace_secs=4)
    finally:
        sc.stop()
    assert json.load(open(out))["total"] == sum(range(40))


def test_shutdown_still_kills_wedged_trainer(tmp_path):
    """The progress-aware grace is still a liveness bound: a trainer that
    stops serving batches (wedged in user code) is terminated once the
    heartbeat goes stale, and shutdown returns promptly."""
    import time as _time
    out = str(tmp_path / "never.json")

    def map_fun(args, ctx):
        import time as _t
        feed = ctx.get_data_feed(train_mode=True)
        while not feed.should_stop():
            feed.next_batch(4)  # prompt consumption: the feed join returns
        _t.sleep(120)  # wedge AFTER the feed: heartbeat goes stale
        with open(args["out"], "w") as f:
            f.write("{}")

    sc = Context(num_executors=1, work_root=str(tmp_path / "engine"))
    try:
        tfc = cluster.run(sc, map_fun, {"out": out}, num_executors=1,
                          input_mode=cluster.InputMode.SPARK)
        tfc.train(sc.parallelize(range(40), 1))
        t0 = _time.monotonic()
        tfc.shutdown(grace_secs=3)
        elapsed = _time.monotonic() - t0
    finally:
        sc.stop()
    assert elapsed < 30, "wedged trainer not reaped within grace bounds"
    assert not os.path.exists(out)
