"""Zero-copy consume-path contracts (the small-batch feed-gap tentpole).

The DataFeed ring path decodes chunks as views INTO the shm mapping and
assembles mapped batches with a single gather per column into a reusable
staging buffer, releasing the ring slot only after that copy. These
tests pin the safety contract (consumed batches never alias ring memory
after slot release), the performance contract (zero read-side column
memcpys and zero per-batch allocations once the staging buffer is
reusable), the slot bookkeeping (held until the last aliasing row is
copied, released exactly once), and the feeder's tail coalescing
(final chunk + EndPartition in ONE ring message).
"""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import frames, manager, node, shm
from tensorflowonspark_tpu.datafeed import DataFeed
from tensorflowonspark_tpu.marker import EndFeed, EndPartition

pytestmark = pytest.mark.skipif(not shm.available(),
                                reason="native shm ring unavailable")


def _ring_feed(name, capacity=1 << 16, mapping=None):
    """(producer_ring, broker, consumer_feed) wired like a node would."""
    shm._load().shmring_unlink(name.encode())
    ring = shm.ShmRing.create(name, capacity=capacity)
    mgr = manager.start(os.urandom(16), ["input"])
    mgr.set("shm_name", name)
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping=mapping or {"x": "x"})
    return ring, mgr, feed


def _close(ring, feed):
    feed._ring.close()
    ring.unlink()
    ring.close()


def test_consumed_batches_never_alias_ring_memory():
    """The materialize contract, zero-copy edition: a batch handed to the
    user must survive the producer wrapping the ring arbitrarily many
    times — if the gather were skipped and the batch aliased the
    mapping, the hammering below would corrupt it silently."""
    ring, mgr, feed = _ring_feed("/tfos-test-zc-alias")
    try:
        x = np.full((4, 1500), 7, np.uint8)
        ring.write_obj(frames.ColumnarChunk([x], names=("x",)), timeout=2.0)
        batch = feed.next_batch(4)
        # the slot was released the moment the gather copied the rows out
        assert feed._ring.pending() == 0
        # hammer far past wraparound while holding `batch`
        for i in range(30):
            ring.write_obj(
                frames.ColumnarChunk([np.full((4, 1500), i % 251, np.uint8)],
                                     names=("x",)), timeout=2.0)
            assert feed._ring.read(timeout=2.0) is not None
        np.testing.assert_array_equal(batch["x"], x)
    finally:
        _close(ring, feed)


def test_staging_reuse_no_alloc_no_read_side_memcpy(monkeypatch):
    """Steady state (repeating batch shape): the consume path performs
    ZERO read-side column memcpys (no ColumnarChunk.materialize at all)
    and zero per-batch allocations — the one copy is the in-place gather
    into the staging buffer, which later batches reuse."""
    calls = []
    orig = frames.ColumnarChunk.materialize

    def counting_materialize(self):
        calls.append(1)
        return orig(self)

    monkeypatch.setattr(frames.ColumnarChunk, "materialize",
                        counting_materialize)
    ring, mgr, feed = _ring_feed("/tfos-test-zc-staging")
    try:
        for i in (1, 2, 3):
            ring.write_obj(
                frames.ColumnarChunk([np.full((4, 64), i, np.uint8)],
                                     names=("x",)), timeout=2.0)
        b1 = feed.next_batch(4)
        np.testing.assert_array_equal(b1["x"], np.full((4, 64), 1, np.uint8))
        b2 = feed.next_batch(4)
        np.testing.assert_array_equal(b2["x"], np.full((4, 64), 2, np.uint8))
        b3 = feed.next_batch(4)
        np.testing.assert_array_equal(b3["x"], np.full((4, 64), 3, np.uint8))
        # every batch landed in the SAME staging buffer: one allocation,
        # then reuse (the documented valid-until-next-call contract)
        assert np.shares_memory(b1["x"], b2["x"])
        assert np.shares_memory(b2["x"], b3["x"])
        stats = feed.stats()
        assert stats["staging_alloc"] == 1
        assert stats["staging_reuse"] == 2
        assert not calls, "read-side materialize memcpy must be gone"
    finally:
        _close(ring, feed)


def test_slot_held_until_fully_copied_released_once():
    """A partially consumed chunk pins its ring slot (the producer must
    not reclaim memory the pending remainder still aliases); consuming
    the remainder releases it exactly once and frees the space."""
    ring, mgr, feed = _ring_feed("/tfos-test-zc-slot", capacity=1 << 16)
    try:
        x = np.arange(8 * 3600, dtype=np.uint8).reshape(8, 3600)
        ring.write_obj(frames.ColumnarChunk([x], names=("x",)), timeout=2.0)
        ring.write_obj(frames.ColumnarChunk([x], names=("x",)), timeout=2.0)
        half = feed.next_batch(4)  # msg1 half-consumed: slot HELD
        np.testing.assert_array_equal(half["x"], x[:4])
        with pytest.raises(TimeoutError):
            # ~29KB free of the ~29KB+pad needed while msg1's slot pins
            # its bytes: the write must block
            ring.write_obj(frames.ColumnarChunk([x[:4]], names=("x",)),
                           timeout=0.3)
        rest = feed.next_batch(4)  # remainder copied out -> slot released
        np.testing.assert_array_equal(rest["x"], x[4:])
        ring.write_obj(frames.ColumnarChunk([x[:4]], names=("x",)),
                       timeout=5.0)  # now fits
    finally:
        _close(ring, feed)


def test_spanning_batch_unpins_slots_before_blocking():
    """A batch spanning several ring messages must unpin consumed
    segments' slots before each further read: read_view's sequential
    contract re-delivers the SAME message while a slot is held (a
    skipped unpin surfaces here as all-zeros duplicated rows), and on
    the liveness side held slots pin bytes the producer needs for the
    rest of the batch (sized so two messages fill the ring)."""
    import threading

    ring, mgr, feed = _ring_feed("/tfos-test-zc-span", capacity=1 << 16)
    try:
        chunks = [frames.ColumnarChunk(
            [np.full((8, 3800), i, np.uint8)], names=("x",))
            for i in range(3)]
        errs = []

        def produce():
            try:
                for c in chunks:
                    ring.write_obj(c, timeout=30.0)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        batch = feed.next_batch(24)  # spans all three messages
        producer.join(timeout=30)
        assert not producer.is_alive() and not errs, (errs or "wedged")
        assert batch["x"].shape == (24, 3800)
        for i in range(3):
            np.testing.assert_array_equal(
                batch["x"][8 * i:8 * (i + 1)],
                np.full((8, 3800), i, np.uint8))
    finally:
        _close(ring, feed)


def test_feed_partition_coalesces_tail_into_one_ring_message():
    """node._feed_partition on the ring sends a small partition as ONE
    message: [chunk, EndPartition] via frames.encode_multi — the
    per-message fixed costs the small-batch regime used to pay twice."""
    shm._load().shmring_unlink(b"/tfos-test-zc-coalesce")
    ring = shm.ShmRing.create("/tfos-test-zc-coalesce", capacity=1 << 20)
    mgr = manager.start(os.urandom(16), ["input"])
    node._NODE_STATE["shm_ring"] = ring
    try:
        records = [(np.full(100, i, np.uint8), np.int64(i))
                   for i in range(10)]
        count = node._feed_partition(iter(records), mgr, "input",
                                     feed_timeout=30)
        assert count == 10
        msg = ring.read(timeout=2.0)
        obj = frames.decode(msg)
        assert isinstance(obj, frames.FrameList)
        assert len(obj) == 2
        assert isinstance(obj[0], frames.ColumnarChunk) and len(obj[0]) == 10
        assert isinstance(obj[1], EndPartition)
        assert ring.pending() == 0, "partition must be exactly one message"
    finally:
        node._NODE_STATE.pop("shm_ring", None)
        ring.unlink()
        ring.close()


def test_datafeed_consumes_coalesced_partitions_end_to_end():
    """Coalesced [chunk, EndPartition] messages round-trip through
    DataFeed with identical semantics: batches never straddle the
    partition boundary and end-of-feed lands."""
    ring, mgr, feed = _ring_feed("/tfos-test-zc-e2e", capacity=1 << 20,
                                 mapping={"x": "x", "y": "y"})
    node._NODE_STATE["shm_ring"] = ring
    try:
        def part(lo, hi):
            return [(np.full(8, i, np.uint8), np.int64(i))
                    for i in range(lo, hi)]

        assert node._feed_partition(iter(part(0, 6)), mgr, "input", 30) == 6
        assert node._feed_partition(iter(part(6, 10)), mgr, "input", 30) == 4
        ring.write_obj(EndFeed(), timeout=2.0)
        sizes = []
        ys = []
        while not feed.should_stop():
            batch = feed.next_batch(4)
            n = len(batch["y"]) if batch else 0
            if n:
                sizes.append(n)
                ys.extend(int(v) for v in batch["y"])
        assert sizes == [4, 2, 4], "batches must not straddle EndPartition"
        assert ys == list(range(10))
        assert feed.stats()["records"] == 10
    finally:
        node._NODE_STATE.pop("shm_ring", None)
        _close(ring, feed)


def test_pack_chunks_bounds_ragged_fallback():
    """A size-targeted accumulation (limit sized from the FIRST record,
    up to FEED_CHUNK_MAX) whose later records are ragged falls back to
    pickled row lists — which must re-split to the legacy FEED_CHUNK
    bound (one unsplittable multi-thousand-record list would hard-fail
    the ring's oversize path and spike the queue pickles)."""
    recs = [(np.zeros(2, np.uint8), np.int64(0))] + \
           [(np.zeros(3, np.uint8), np.int64(i)) for i in range(600)]
    out = node._pack_chunks(recs)
    assert all(isinstance(c, list) for c in out)
    assert max(len(c) for c in out) <= node.FEED_CHUNK
    assert sum(len(c) for c in out) == 601
    flat = [r for c in out for r in c]
    assert all(int(flat[1 + i][1]) == i for i in range(600))


def test_queue_single_chunk_passthrough_stays_zero_copy():
    """The queue transport's steady state (one owned chunk per batch)
    keeps its zero-copy pass-through: output columns are views of the
    chunk's arrays, no gather, no staging."""
    mgr = manager.start(os.urandom(16), ["input"])
    q = mgr.get_queue("input")
    x = np.arange(20, dtype=np.float32).reshape(5, 4)
    q.put(frames.ColumnarChunk([x], names=("x",)))
    q.put(EndFeed())
    feed = DataFeed(mgr, train_mode=True, input_mapping={"x": "x"})
    batch = feed.next_batch(5)
    assert np.shares_memory(batch["x"], x)
    assert feed.stats()["staging_alloc"] == 0
