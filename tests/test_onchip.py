"""On-chip validation hooks — skipped until real TPU compute is present.

VERDICT r3 task 4: the flash kernels must be re-validated on Mosaic in
every hardware window, so the check lives in the suite and re-arms
automatically. The suite pins itself to CPU (conftest), so these tests
run the harnesses in SUBPROCESSES with the CPU pin stripped; they skip
— loudly, with the reason — unless ``TFOS_ON_CHIP=1`` is set by an
operator who has confirmed tunnel compute (a dead tunnel makes any
device call hang, which must never stall the default gate). `make
onchip` is the operator entry point; this is the suite-level record.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("TFOS_ON_CHIP") != "1",
        reason="needs live TPU compute: set TFOS_ON_CHIP=1 after "
               "confirming the tunnel serves a matmul (see make onchip)"),
]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_onchip(script, *args, timeout=1800):
    # The conftest stashed the real pool address (TFOS_AXON_IPS) before
    # blanking PALLAS_AXON_POOL_IPS; without it the child would target
    # nothing (or the wrong host) and hang until the subprocess timeout.
    pool = os.environ.get("TFOS_AXON_IPS")
    if not pool:
        pytest.fail(
            "TFOS_ON_CHIP=1 but no pool address: export TFOS_AXON_IPS "
            "(the PALLAS_AXON_POOL_IPS value outside the test harness)")
    env = dict(os.environ)
    # undo the conftest CPU pin for the child: it must see the chip, and
    # multi-node bootstrap reverts to the operator's pre-harness value
    # (conftest stashed it) or the non-test default
    env.pop("JAX_PLATFORMS", None)
    orig = env.pop("TFOS_TPU_DISTRIBUTED_ORIG", None)
    if orig is not None:
        env["TFOS_TPU_DISTRIBUTED"] = orig
    else:
        env.pop("TFOS_TPU_DISTRIBUTED", None)
    env["PALLAS_AXON_POOL_IPS"] = pool
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, script)] + list(args),
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_ROOT)


def test_flash_kernels_on_chip():
    """Mosaic-compiled flash fwd/bwd parity + S=4096 memory win."""
    out = _run_onchip("scripts/flash_on_chip.py")
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["parity_ok"] is True, summary


def test_bench_fed_on_chip():
    """The north-star number: cluster-fed throughput on the real chip."""
    out = _run_onchip("bench.py")
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result.get("error") is None, result
    # bench silently downgrades to the CPU smoke off-chip — a green run
    # must prove it actually measured the chip
    assert result["metric"] == \
        "resnet50_cluster_fed_images_per_sec_per_chip", result
    assert result["value"] > 0, result
